"""ContivAgent: the vswitch-node process, all plugins wired.

Reference analogs: flavors/contiv FlavorContiv.Inject
(contiv_flavor.go:102-191 — the DI graph of ~20 plugins) and
cmd/contiv-agent/main.go:28-49 (event loop + SIGTERM graceful close).

Startup order mirrors the reference's Init/AfterInit phases (SURVEY.md
§3.1): data store → node ID → IPAM → dataplane + renderers → policy/
service plugins → CNI server → watchers subscribed → first resync →
ready. The kvstore watch bridge is the cn-infra kvdbsync analog: KSR
writes `k8s/<type>/...` keys; the bridge deserializes model objects and
fans them out to the policy cache and service processor.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Optional

from vpp_tpu.agent import node_id as node_id_mod
from vpp_tpu.agent.node_id import NodeIDAllocator
from vpp_tpu.cni.containeridx import ContainerIndex
from vpp_tpu.cni.server import RemoteCNIServer
from vpp_tpu.cni.transport import CNITransportServer
from vpp_tpu.cmd.config import AgentConfig
from vpp_tpu.health.statuscheck import HealthHTTPServer, PluginState, StatusCheck
from vpp_tpu.health.stn import STNDaemon
from vpp_tpu.hoststack.session_rules import SessionRuleEngine
from vpp_tpu.ipam.ipam import IPAM
from vpp_tpu.ir.rule import PodID
from vpp_tpu.ksr import model as m
from vpp_tpu.kvstore.proxy import KVProxy
from vpp_tpu.net.linux import IpCmdError
from vpp_tpu.kvstore.store import Broker, KVEvent, KVStore, Op
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.vector import Disposition
from vpp_tpu.policy import PolicyCache, PolicyConfigurator, PolicyProcessor
from vpp_tpu.renderer.tpu import TpuRenderer
from vpp_tpu.renderer.vpptcp import VpptcpRenderer
from vpp_tpu.service import ServiceConfigurator, ServiceProcessor
from vpp_tpu.stats.collector import StatsCollector, register_control_plane_metrics
from vpp_tpu.stats.prometheus import StatsHTTPServer
from vpp_tpu.trace import spans

log = logging.getLogger("vpp_tpu.agent")

# KSR publishes under this store prefix (the reference's
# /vnf-agent/contiv-ksr/ microservice-label prefix,
# flavors/contiv/contiv_flavor.go:129-138).
KSR_PREFIX = "ksr/"


def _ksr_key(ev_key: str) -> str:
    """Strip the KSR store prefix off a watched key for parse_key()."""
    return ev_key[len(KSR_PREFIX):] if ev_key.startswith(KSR_PREFIX) else ev_key


class ContivAgent:
    def __init__(self, config: Optional[AgentConfig] = None,
                 store: Optional[KVStore] = None,
                 dataplane: Optional[Dataplane] = None,
                 mesh_node_resolver=None):
        """``store`` injection lets tests (and multi-agent simulations)
        share one in-memory store; production passes None and gets the
        configured backend — a RemoteKVStore against the cluster's
        KVServer when ``store_url`` is set (the deployed-etcd analog),
        else a persisted local store.

        ``dataplane`` injection is the mesh-mode path
        (parallel/runtime.MeshRuntime): the agent drives a cluster NODE
        HANDLE whose swap publishes a full multi-chip epoch, instead of
        owning a standalone single-chip dataplane.

        ``mesh_node_resolver`` maps a peer's allocator node id to its
        mesh position (-1 = not on this mesh). With a resolver set,
        routes toward on-mesh peers carry the mesh position as
        ``node_id`` — the cluster step hands those packets to the
        all_to_all ICI fabric — and off-mesh peers get edge routes
        (node_id=-1) that leave via VXLAN, exactly the SURVEY §2.4
        fabric/edge split."""
        self.config = config or AgentConfig()
        c = self.config
        self.mesh_node_resolver = mesh_node_resolver

        # --- data store + proxy (cn-infra kvdbsync analog) ---
        if store is None:
            from vpp_tpu.kvstore.client import connect_store

            store = connect_store(c.store_url, persist_path=c.persist_path)
        self.store = store
        self.proxy = KVProxy(self.store)
        self._watch_cancels = []

        # --- statuscheck ---
        self.statuscheck = StatusCheck()
        self._report_core = self.statuscheck.register("core")
        self._report_policy = self.statuscheck.register("policy")
        self._report_service = self.statuscheck.register("service")

        # --- node identity + IPAM ---
        self.node_allocator = NodeIDAllocator(
            self.store, c.node_name,
            liveness_ttl_s=c.node_liveness_ttl_s)
        self.node_id = self.node_allocator.get_or_allocate()
        broker = Broker(self.store, f"agent/{c.node_name}/")
        self.ipam = IPAM(self.node_id, c.ipam, broker=broker)

        # --- data plane + renderers ---
        self.dataplane = (
            dataplane if dataplane is not None else Dataplane(c.dataplane)
        )
        # api-trace: enabled BEFORE any staging so the journal opens with
        # this agent's base vswitch config and replays to identical
        # tables (reference contiv-vswitch.conf:13-15 `api-trace { on }`)
        if c.txn_journal_path:
            self.dataplane.enable_journal(c.txn_journal_path)
            self.dataplane.builder.txn_label = "base-vswitch-config"
        self.uplink_if = self.dataplane.add_uplink()
        self.host_if = self.dataplane.add_host_interface()
        self.dataplane.set_vtep(int(self.ipam.vxlan_ip_address()))
        # Cluster-egress: default route out the uplink, source-NAT'd to
        # the node IP so external replies return through this node
        # (reference: service configurator SNAT pool for traffic leaving
        # the cluster, configurator_impl.go:258-264). Staged here,
        # published by start()'s base-config swap.
        from vpp_tpu.pipeline.vector import ip4

        self.dataplane.builder.add_route(
            "0.0.0.0/0", self.uplink_if, Disposition.REMOTE, snat=True
        )
        # Cluster-internal subnets must never leak out the SNAT egress:
        # a drop route for the whole pod/host supernets that per-peer
        # routes (longest prefix) override — traffic to a removed node
        # drops instead of escaping NAT'd (reference: only pod-external
        # traffic hits the SNAT pool).
        self.dataplane.builder.add_route(
            str(self.ipam.pod_subnet), -1, Disposition.DROP
        )
        self.dataplane.builder.add_route(
            str(self.ipam.vpp_host_subnet), -1, Disposition.DROP
        )
        if c.io.host_interconnect and c.io.control_socket:
            # this node's own host-interconnect /24 punts to the host
            # stack (longest prefix wins over the supernet drop above)
            # — the routesToHost analog (host.go:92-110). Gated on the
            # interconnect actually being wired: without a host
            # transport these flows must stay attributed FIB drops, not
            # phantom punts that die in tx dispatch
            self.dataplane.builder.add_route(
                str(self.ipam.vpp_host_network), self.host_if,
                Disposition.HOST
            )
        self.dataplane.builder.set_snat_ip(
            ip4(str(self.ipam.node_ip_address()))
        )
        self.tpu_renderer = TpuRenderer(self.dataplane)
        self.session_engine = SessionRuleEngine()
        self.vpptcp_renderer = VpptcpRenderer(
            self.session_engine, self._pod_ns_index
        )

        # --- policy plugin (cache → processor → configurator) ---
        self.policy_cache = PolicyCache()
        self.policy_configurator = PolicyConfigurator(
            self.policy_cache,
            parallel_commits=c.parallel_renderer_commits,
        )
        self.policy_configurator.register_renderer(self.tpu_renderer)
        self.policy_configurator.register_renderer(self.vpptcp_renderer)
        self.policy_processor = PolicyProcessor(
            self.policy_cache, self.policy_configurator
        )

        # --- service plugin ---
        self.service_configurator = ServiceConfigurator(
            self.dataplane,
            node_ips=[str(self.ipam.node_ip_address())],
        )
        self.service_processor = ServiceProcessor(
            self.service_configurator, node_name=c.node_name
        )

        # --- CNI ---
        self.container_index = ContainerIndex(broker)
        # pod wiring: with an IO-daemon control socket configured, CNI
        # Adds create real veth pairs and attach them to the daemon at
        # runtime (VERDICT r2 Missing #1; reference pod.go:262-452)
        wirer = None
        self.io_ctl = None
        if c.io.control_socket:
            from vpp_tpu.cni.wiring import VethPodWirer
            from vpp_tpu.io.control import IOControlClient

            self.io_ctl = IOControlClient(c.io.control_socket)
            wirer = VethPodWirer(
                self.io_ctl, gateway_ip=str(self.ipam.pod_gateway_ip())
            )
        # VPP↔host interconnect (host.go:105-200): wired in start()
        # once the IO daemon serves the control socket
        self.host_interconnect = None
        if c.io.host_interconnect and self.io_ctl is not None:
            from vpp_tpu.cni.wiring import HostInterconnectWirer

            self.host_interconnect = HostInterconnectWirer(
                self.io_ctl, self.ipam
            )
        self.cni_server = RemoteCNIServer(
            self.dataplane, self.ipam, self.container_index,
            on_pod_change=self._on_local_pod_change,
            wirer=wirer,
        )
        self.cni_transport: Optional[CNITransportServer] = None
        self.cli_transport: Optional[CNITransportServer] = None
        self.vcl_admission = None  # VclAdmissionServer when vcl_socket set
        self.mesh_runtime = None   # set by Mesh/MultiHostRuntime (show mesh)

        # --- crash-consistent session snapshot/restore (ISSUE 8) ---
        # only for a standalone (materialized) dataplane: a mesh node
        # staging handle's session state belongs to the cluster epoch
        self.snapshotter = None
        if c.snapshot_path and self.dataplane.tables is not None:
            from vpp_tpu.pipeline.snapshot import SessionSnapshotter

            self.snapshotter = SessionSnapshotter(
                self.dataplane, c.snapshot_path,
                chunk_buckets=c.snapshot_chunk_buckets,
                pace_s=c.snapshot_pace_s,
            )

        # --- per-packet ML model source (ISSUE 10; vpp_tpu/ml/) ---
        # only with the stage configured on AND a standalone dataplane
        # (a mesh staging handle's tables belong to the cluster epoch)
        self.ml_source = None
        if (c.ml_model_path
                and getattr(c.dataplane, "ml_stage", "off") != "off"
                and self.dataplane.tables is not None):
            from vpp_tpu.ml.loader import MlModelSource

            self.ml_source = MlModelSource(self.dataplane,
                                           c.ml_model_path)

        # --- observability ---
        self.stats = StatsCollector(self.dataplane, self.container_index)
        # degraded-mode surface: kvstore reachability/staleness +
        # snapshot age/outcomes ride the same registry
        self.stats.set_store(self.store)
        if self.snapshotter is not None:
            self.stats.set_snapshotter(self.snapshotter)
        if self.ml_source is not None:
            self.stats.set_ml(self.ml_source)
        # control-plane latency histograms: propagation SLO + txn commit
        # observed at the epoch swap, CNI add/del at the CNI server
        self.cp_metrics = register_control_plane_metrics(self.stats.registry)
        self.dataplane.propagation_hist = self.cp_metrics["config_propagation"]
        self.dataplane.txn_commit_hist = self.cp_metrics["txn_commit"]
        self.cni_server.duration_hist = self.cp_metrics["cni_request"]
        self.stats_http: Optional[StatsHTTPServer] = None
        self.health_http: Optional[HealthHTTPServer] = None

        # --- STN bootstrap (contiv-init analog) ---
        self.stn: Optional[STNDaemon] = None

        # --- packet IO (rings + pump, created in start() when enabled) ---
        self.io_rings = None
        self.io_pump = None
        # mesh mode: the MeshRuntime owns per-node rings and ONE
        # ClusterPump stepping the fabric — this agent must not create
        # its own single-node device bridge
        self._external_io = False

        # peers with installed routes: node_id -> peer vtep ip
        self._peer_routes = {}
        self._closed = threading.Event()
        self._maint_thread: Optional[threading.Thread] = None
        # session idle timeout in clock ticks; None = the dataplane
        # config's sess_max_age (wall-clock based — the VPP session/NAT
        # timer analog; lookups also enforce it in-kernel)
        self.session_max_age = None

    # --- contiv.API analogs ---
    def _pod_ns_index(self, pod: PodID) -> int:
        """GetNsIndex analog: a pod's app-namespace index is its
        dataplane interface index (unique per pod on this node)."""
        return self.dataplane.pod_if.get(pod, -1)

    def _on_local_pod_change(self) -> None:
        """A pod was wired/unwired by CNI: re-render policies (the
        reference reacts to the ETCD echo; we shortcut in-process)."""
        self.policy_processor.resync()

    # --- lifecycle ---
    def start(self, netlink_backend=None) -> None:
        c = self.config
        # STN bootstrap (contiv-init main.go:66-119): steal the
        # configured NIC before bringing up the data plane's uplink path
        if c.stn_interface and netlink_backend is not None:
            self.stn = STNDaemon(
                netlink_backend, persist_path=c.stn_persist_path
            )
            self.stn.steal(c.stn_interface)
        # multi-tenant gateway mode (ISSUE 14; vpp_tpu/tenancy/):
        # stage the configured tenants BEFORE the base swap so the
        # first epoch already derives/slices/limits per tenant —
        # entries were validated at config load
        if c.tenants:
            for e in c.tenants:
                kw = {k: v for k, v in e.items() if k != "id"}
                self.dataplane.builder.set_tenant(e["id"], **kw)
        # publish the base vswitch config (uplink/host interfaces staged
        # in __init__) before anything can send through those interfaces
        # — configureVswitchConnectivity's final txn in the reference
        self.dataplane.swap()
        # warm restart (ISSUE 8): adopt the last crash-consistent
        # session snapshot generation BEFORE any traffic, so
        # established flows (and the fastpath hit rate) survive the
        # restart; a refusal (torn/corrupt/geometry) cold-starts
        # cleanly and the outcome counter says why
        if self.snapshotter is not None:
            try:
                if self.snapshotter.restore_into():
                    log.info("session table restored warm from %s",
                             c.snapshot_path)
            except Exception:
                log.exception("session restore failed (cold start)")
        # initial ML model publish (ISSUE 10): before traffic, so the
        # first packets already score; a refusal is a counted outcome
        # and the stage stays compiled out until a good load lands
        if self.ml_source is not None:
            self.ml_source.poll()
        # packet-IO front-end: shared-memory rings + the dataplane pump
        # (the vpp-tpu-io daemon attaches to the same shm and owns the
        # NIC/TAP endpoints — VERDICT r1 Missing #1). Created before the
        # CNI resync: resync re-attaches pod veths through the daemon's
        # control socket and those packets land in these rings.
        if c.io.enabled and not self._external_io:
            from vpp_tpu.io.pump import DataplanePump
            from vpp_tpu.io.rings import IORingPair

            self.io_rings = IORingPair(
                n_slots=c.io.n_slots, snap=c.io.snap,
                shm_name=c.io.shm_name or None, create=True,
            )
            # reflex-plane latency governor + priority lane (ISSUE
            # 13; io/governor.py): built only when configured — an
            # SLO of 0 keeps the open-loop pump, and the priority
            # lane works with or without the governor
            governor = None
            if c.io.latency_slo_us > 0:
                from vpp_tpu.io.governor import LatencyGovernor

                governor = LatencyGovernor(
                    c.io.latency_slo_us,
                    tick_s=c.io.governor_tick_s,
                    hysteresis_pct=c.io.governor_hysteresis_pct,
                    brownout_ticks=c.io.governor_brownout_ticks,
                    recover_ticks=c.io.governor_recover_ticks,
                )
            priority = None
            if (c.io.priority_ports or c.io.priority_prefixes
                    or c.io.priority_protos):
                from vpp_tpu.io.governor import PriorityFilter

                priority = PriorityFilter(
                    ports=c.io.priority_ports,
                    prefixes=c.io.priority_prefixes,
                    protos=c.io.priority_protos,
                )
            # tenant lanes (ISSUE 14): the pump's weighted-fair
            # classifier mirrors the staged tenant registry (same
            # prefixes/weights/VNIs the device derivation uses)
            tenant_cls = None
            if c.tenants:
                from vpp_tpu.tenancy.sched import TenantClassifier

                tenant_cls = TenantClassifier(c.tenants)
            self.io_pump = DataplanePump(
                self.dataplane, self.io_rings,
                max_batch=c.io.max_batch, depth=c.io.depth,
                workers=c.io.workers,
                max_inflight=c.io.max_inflight,
                fetch_workers=c.io.fetch_workers,
                chain_k=c.io.chain_k,
                mode=c.io.pump_mode,
                ring_slots=c.io.io_ring_slots,
                ring_windows=c.io.io_ring_windows,
                ring_fault_limit=c.io.io_ring_fault_limit,
                governor=governor,
                priority=priority,
                tenants=tenant_cls,
                tenant_quantum=c.io.io_tenant_quantum,
                # ICMP errors (time-exceeded/unreachable) originate from
                # the node's pod gateway address — the hop traceroute
                # shows (reference: VPP ip4-icmp-error)
                icmp_src_ip=(int(self.ipam.pod_gateway_ip())
                             if c.io.icmp_errors else 0),
            )
            # warm every dispatch bucket rung before serving — a lazy
            # mid-traffic rung compile would stall the rx rings
            t0 = time.monotonic()
            rungs = self.io_pump.warm()
            log.info("pump dispatch rungs %s warmed in %.1fs",
                     rungs, time.monotonic() - t0)
            self.io_pump.start()
        if c.io.enabled and c.io.plan_path:
            # also in mesh mode (_external_io): vpp-tpu-init waits for
            # this file to launch the node's vpp-tpu-io daemon, and the
            # MeshRuntime's rings use the same config geometry/shm name
            self._write_io_plan()
        if self.io_pump is not None and not self._external_io:
            # export pump counters over Prometheus. In mesh mode
            # (_external_io) io_pump is the SHARED ClusterPump whose
            # counters are cluster-wide — exporting it from every
            # agent would overcount by n_nodes, so the MeshRuntime
            # attaches it to one designated collector instead.
            self.stats.set_pump(self.io_pump)
        if self.io_ctl is not None:
            # the rx_full drop cause is counted in the IO daemon (a
            # separate process): feed its stats over the control
            # socket so vpp_tpu_pump_drops_total{reason="rx_full"}
            # reports real overflow, not a structural 0. A dedicated
            # SHORT-timeout client: the scrape path must not inherit
            # the control client's 10 s budget when the daemon wedges
            # (the collector additionally caches + backs off).
            from vpp_tpu.io.control import IOControlClient as _IoCtl

            self.stats.set_io_daemon(
                _IoCtl(c.io.control_socket, timeout=0.5).stats)
        if self.host_interconnect is not None:
            # vpp-tpu-init only STARTS the IO daemon after it sees the
            # plan file written above, so on a cold boot the control
            # socket appears a moment later — wait for it instead of
            # losing the race (CNI pod wiring never hits this because
            # Adds arrive only once the daemon is up)
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    self.host_interconnect.wire(self.host_if)
                    break
                except IpCmdError:
                    # ip(8)/daemon command failures are permanent
                    # (missing CAP_NET_ADMIN, EEXIST, ...) — retrying
                    # them only re-runs wire()'s create+rollback for a
                    # minute; surface immediately
                    raise
                except OSError:
                    # the boot race this wait exists for: control
                    # socket not yet bound (FileNotFoundError /
                    # ConnectionRefusedError)
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.5)
            log.info("host interconnect wired (%s <-> %s)",
                     self.host_interconnect.host_end,
                     self.host_interconnect.vsw_end)
        # resync persisted pods before serving (restart path)
        n = self.cni_server.resync()
        if n:
            log.info("resynced %d persisted pods", n)
        self._subscribe_watchers()
        # first resync: replay existing KSR state from the store through
        # the same handlers — the watch bridge only sees future events,
        # but KSR typically reflected pods/policies/services before this
        # agent (re)started (the reference's startup resync, SURVEY §3.1)
        self._resync_from_store()
        # node events: learn peers that registered before we started
        # (node_events.go resync), then publish our own IPs for them.
        # Only LIVE peers (current liveness lease): allocatedIDs claims
        # deliberately survive crashes for ID reuse, so routing from
        # them would resurrect routes to dead nodes that lease expiry
        # already tore down on everyone else.
        for node_id, info in self.node_allocator.list_live_nodes().items():
            self._apply_node(node_id, info)
        self.node_allocator.publish_ips(
            str(self.ipam.node_ip_address()),
        )
        # lease-attached liveness: if this agent dies without cleanup,
        # the lease expires server-side and every peer's liveness watch
        # removes its routes to us (VERDICT r2 Next #8)
        try:
            self.node_allocator.publish_liveness(
                str(self.ipam.node_ip_address())
            )
        except Exception:
            log.exception("liveness publish failed (continuing)")
        self.cni_server.set_ready()
        if c.vcl_socket:
            # the ldpreload endpoint: unmodified apps launched with
            # vcl_env() get session-rule admission on every
            # connect()/accept() against this node's session rules
            # (reference: VCL ldpreload, tests/ld_preload*). A policy
            # endpoint, not observability — independent of serve_http.
            from vpp_tpu.hoststack.admission import VclAdmissionServer

            self.vcl_admission = VclAdmissionServer(
                self.session_engine, c.vcl_socket
            ).start()
            self.stats.set_vcl(self.vcl_admission)
        if c.serve_http:
            self.cni_transport = CNITransportServer(
                c.cni_socket, self.cni_server.dispatch
            )
            self.cni_transport.start()
            if c.cli_socket:
                # the vppctl transport: one-shot debug commands against
                # the RUNNING agent (vpp-tpu-ctl "show interface" ...)
                from vpp_tpu.cli import DebugCLI

                # `vpp-tpu-ctl trace add N` lazily attaches the packet
                # tracer to the dataplane; disarmed it is a zero-cost
                # early return per frame
                cli = DebugCLI(
                    self.dataplane, stats=self.stats,
                    pump=self.io_pump, io_ctl=self.io_ctl,
                    session_engine=self.session_engine,
                    mesh_runtime=self.mesh_runtime,
                    store=self.store,
                    snapshotter=self.snapshotter,
                    ml_source=self.ml_source,
                )

                def _cli_dispatch(method: str, params: dict) -> dict:
                    if method != "run":
                        return {"result": 1,
                                "error": f"unknown method {method!r}"}
                    try:
                        return {"result": 0,
                                "output": cli.run(str(params.get("line", "")))}
                    except Exception as e:  # noqa: BLE001 — debug path
                        return {"result": 1,
                                "error": f"{type(e).__name__}: {e}"}

                # the transport unlinks an existing socket on bind, so
                # a path collision would silently STEAL another live
                # agent's CLI socket — probe first and refuse instead
                live = False
                try:
                    from vpp_tpu.cni.transport import cni_call

                    cni_call(c.cli_socket, "run", {"line": "help"},
                             timeout=1.0)
                    live = True
                except TimeoutError:
                    # connected but no answer within the window: a LIVE
                    # but busy agent (e.g. mid jit-compile holding the
                    # dataplane lock) — stealing its socket is exactly
                    # what this probe exists to prevent. Refuse takeover;
                    # only connection-refused/absent means stale.
                    live = True
                except (OSError, RuntimeError, ValueError):
                    pass  # nothing answering: stale or absent socket
                if live:
                    log.warning(
                        "cli socket %s already served by a live agent; "
                        "not taking it over", c.cli_socket)
                else:
                    try:
                        self.cli_transport = CNITransportServer(
                            c.cli_socket, _cli_dispatch
                        )
                        self.cli_transport.start()
                    except OSError as e:
                        # a debug convenience must never take the
                        # node's data plane down with it
                        log.warning("cli socket %s unavailable: %s",
                                    c.cli_socket, e)
                        self.cli_transport = None
            self.stats_http = StatsHTTPServer(
                self.stats.registry, port=c.stats_port, host=c.http_host
            )
            # debug surface next to the scrape paths: span timelines and
            # the txn journal with per-stage timings (both JSON; the
            # CLI's `show spans` / `show config-history` render the
            # same data for humans). `/` indexes everything served.
            self.stats_http.add_page("/debug/spans", self.debug_spans_json)
            self.stats_http.add_page("/debug/txns", self.debug_txns_json)
            self.stats_http.add_page("/debug/jit", self.debug_jit_json)
            self.stats_http.start()
            self.health_http = HealthHTTPServer(
                self.statuscheck, port=c.health_port, host=c.http_host
            )
            self.health_http.start()
        self._report_core(PluginState.OK)
        self._report_policy(PluginState.OK)
        self._report_service(PluginState.OK)
        if c.serve_http:
            self._maint_thread = threading.Thread(
                target=self._maintenance_loop, daemon=True,
                name="agent-maintenance",
            )
            self._maint_thread.start()

    def _write_io_plan(self) -> None:
        """Publish the IO-daemon launch plan (ring geometry, interface
        indices, overlay parameters) once the shm rings exist —
        vpp-tpu-init waits for this file and starts vpp-tpu-io with
        matching flags (the supervised-start handshake of the
        reference's contiv-init, main.go:201-273)."""
        import json as _json
        import os as _os

        c = self.config
        plan = {
            "shm": c.io.shm_name,
            "slots": c.io.n_slots,
            "snap": c.io.snap,
            "uplink_if": self.uplink_if,
            "host_if": self.host_if,
            "uplink_interface": c.io.uplink_interface,
            "vtep": int(self.ipam.vxlan_ip_address()),
            "vni": c.io.vni,
            "control_socket": c.io.control_socket,
        }
        _os.makedirs(_os.path.dirname(c.io.plan_path) or ".",
                     exist_ok=True)
        tmp = c.io.plan_path + ".tmp"
        with open(tmp, "w") as f:
            _json.dump(plan, f)
        _os.replace(tmp, c.io.plan_path)

    # --- debug pages (served by the stats HTTP server) ---
    @staticmethod
    def debug_spans_json() -> str:
        """/debug/spans: recorded span timelines grouped by trace."""
        return spans.RECORDER.to_json()

    @staticmethod
    def debug_jit_json() -> str:
        """/debug/jit: the runtime jit-compile guard's full state —
        per (step variant, argument-shape signature) compile counts and
        the recompile violations (ISSUE 5; the scrapeable twin of
        ``vpp_tpu_jit_compiles_total`` with the shape axis kept)."""
        import json as _json

        from vpp_tpu.pipeline.dataplane import (
            jit_compile_counts,
            jit_compile_totals,
            jit_recompiles,
        )

        return _json.dumps({
            "totals": jit_compile_totals(),
            "compiles": [
                {"step": label, "shapes": repr(sig), "count": n}
                for (label, sig), n in sorted(jit_compile_counts().items())
            ],
            "recompiled": [
                {"step": label, "shapes": repr(sig), "count": n}
                for (label, sig), n in sorted(jit_recompiles().items())
            ],
        }, indent=1)

    # /debug/txns tail cap: a long-lived agent's journal grows without
    # bound; the debug page serves the recent history, not an export
    DEBUG_TXNS_LIMIT = 200

    def debug_txns_json(self) -> str:
        """/debug/txns: journal tail (last DEBUG_TXNS_LIMIT entries,
        bounded tail read — never a full-file parse per scrape) joined
        with each applied txn's span timeline (per-stage exclusive
        seconds, keyed by swap epoch)."""
        import json as _json

        journal = self.dataplane.journal
        entries = (journal.load_tail_entries(self.DEBUG_TXNS_LIMIT)
                   if journal is not None else [])
        by_epoch = spans.RECORDER.epoch_timings()
        out = []
        for e in entries:
            epoch = e.get("epoch")
            trace_id, stages = by_epoch.get(epoch, (None, None))
            out.append({
                "epoch": epoch,
                "t": e.get("t"),
                "label": e.get("label", ""),
                "ops": len(e.get("ops", [])),
                "trace_id": trace_id,
                "stage_seconds": stages,
            })
        return _json.dumps({
            "applied": journal.applied if journal is not None else 0,
            "shown": len(entries),
            "torn_lines": journal.torn_lines if journal is not None else 0,
            "txns": out,
        })

    def maintenance_tick(self) -> None:
        """One round of periodic upkeep: age sessions, publish stats,
        poll health probes. Called by the background loop; callable
        directly in tests."""
        try:
            # lazy: when the in-step amortized sweep has cycled the
            # whole table since the last tick, the bulk pass is skipped
            # (steady-state aging rides the fused step); idle nodes
            # still reclaim here
            self.dataplane.expire_sessions(self.session_max_age,
                                           lazy=True)
        except Exception:
            log.exception("session expiry failed")
        try:
            # interval-paced incremental snapshot: dirty chunks drain
            # off the hot path on this maintenance thread (failures
            # mark the snapshotter degraded, never kill the tick —
            # the liveness keepalive below must always run). A
            # persistent-mode pump threads its session state privately
            # through the resident ring: graft a consistent copy into
            # dp.tables first, or the snapshot would capture the
            # launch-time state against an advancing clock.
            if self.snapshotter is not None and self.snapshotter.due(
                    self.config.snapshot_interval_s):
                # gated on the snapshot actually being due: the ring
                # checkpoint is a full device copy of the session
                # columns and must not run on every 5 s tick
                sync = getattr(self.io_pump, "sync_sessions", None)
                if callable(sync):
                    sync()
                self.snapshotter.maybe_snapshot(
                    self.config.snapshot_interval_s)
        except Exception:
            log.exception("session snapshot failed")
        try:
            # ML model hot reload: mtime-gated, so the tick is one
            # stat() in steady state; a refused artifact keeps the
            # previous model serving (counted, degraded{component=ml})
            if self.ml_source is not None:
                self.ml_source.poll()
        except Exception:
            log.exception("ml model poll failed")
        try:
            self.stats.publish()
        except Exception:
            log.exception("stats publish failed")
        try:
            self.statuscheck.run_probes()
        except Exception:
            log.exception("probe round failed")
        try:
            self.node_allocator.liveness_keepalive()
        except Exception:
            log.exception("liveness keepalive failed")
        # in-process stores have no server-side sweeper; expire overdue
        # leases here so liveness semantics hold in dev mode too
        sweep = getattr(self.store, "sweep_leases", None)
        if callable(sweep):
            try:
                sweep()
            except Exception:
                log.exception("lease sweep failed")

    def _maintenance_loop(self, interval: float = 5.0) -> None:
        while not self._closed.wait(interval):
            self.maintenance_tick()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for cancel in self._watch_cancels:
            cancel()
        for srv in (self.cni_transport, self.cli_transport,
                    self.stats_http, self.health_http):
            if srv is not None:
                srv.close()
        if self.vcl_admission is not None:
            self.vcl_admission.stop()
        self.proxy.close()
        pump_stopped = True
        if self.io_pump is not None and not self._external_io:
            # mesh mode (_external_io): io_pump is the SHARED ClusterPump
            # wired in for `show io` — its lifecycle belongs to the
            # MeshRuntime; one agent closing must not halt fabric IO for
            # every other node
            pump_stopped = self.io_pump.stop(join_timeout=30.0)
        if self.io_rings is not None:
            if pump_stopped:
                self.io_rings.close(unlink=bool(self.config.io.shm_name))
            else:
                # A wedged pump still holds ring pointers; freeing the
                # buffers under it would be a use-after-free into shared
                # memory. Leak the mapping (process exit reclaims it).
                log.error("pump did not stop; leaving rings mapped")
        if self.host_interconnect is not None:
            try:
                self.host_interconnect.unwire(self.host_if)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                log.warning("host interconnect unwire failed")
        if self.stn is not None:
            self.stn.revert_all()
        if self.snapshotter is not None:
            # a clean shutdown's parting snapshot: the next start
            # restores the freshest possible generation — the pump
            # merged its final ring sessions into dp.tables above, and
            # final_snapshot waits out any maintenance drain still in
            # flight (which began from pre-merge state) before
            # draining once more (best effort — failures land in the
            # degraded counters)
            self.snapshotter.final_snapshot()
        if self.store.persist_path:
            self.store.save()

    # --- the kvdbsync watch bridge ---
    def _traced(self, kind: str, handler):
        """Wrap a watch handler in an "agent" dispatch span, joining the
        active config trace (or rooting one for out-of-band events)."""
        def dispatch(ev: KVEvent) -> None:
            with spans.RECORDER.span(
                "agent", f"dispatch {kind} {ev.key}", node=self.config.node_name,
            ):
                handler(ev)
        return dispatch

    def _subscribe_watchers(self) -> None:
        sub = self.proxy.watch
        traced = self._traced
        self._watch_cancels = [
            sub(KSR_PREFIX + m.key_prefix(m.Pod.TYPE),
                traced("pod", self._on_pod_event)),
            sub(KSR_PREFIX + m.key_prefix(m.Policy.TYPE),
                traced("policy", self._on_policy_event)),
            sub(KSR_PREFIX + m.key_prefix(m.Namespace.TYPE),
                traced("namespace", self._on_namespace_event)),
            sub(KSR_PREFIX + m.key_prefix(m.Service.TYPE),
                traced("service", self._on_service_event)),
            sub(KSR_PREFIX + m.key_prefix(m.Endpoints.TYPE),
                traced("endpoints", self._on_endpoints_event)),
            sub(node_id_mod.ID_PREFIX,
                traced("node", self._on_node_event)),
            sub(node_id_mod.LIVENESS_PREFIX,
                traced("liveness", self._on_liveness_event)),
        ]

    def _resync_from_store(self) -> None:
        handlers = {
            m.Pod.TYPE: self._on_pod_event,
            m.Namespace.TYPE: self._on_namespace_event,
            m.Policy.TYPE: self._on_policy_event,
            m.Service.TYPE: self._on_service_event,
            m.Endpoints.TYPE: self._on_endpoints_event,
        }
        for obj_type, handler in handlers.items():
            prefix = KSR_PREFIX + m.key_prefix(obj_type)
            for key, value in self.store.list_values(prefix).items():
                handler(KVEvent(op=Op.PUT, key=key, value=value,
                                prev_value=None, rev=0))

    # --- node events (plugins/contiv/node_events.go:34,184-252) ---
    def _on_node_event(self, ev: KVEvent) -> None:
        try:
            node_id = int(ev.key[len(node_id_mod.ID_PREFIX):])
        except ValueError:
            return
        if node_id == self.node_id:
            return
        if ev.op == Op.PUT:
            self._apply_node(node_id, ev.value or {})
        else:
            self._remove_node(node_id)

    def _on_liveness_event(self, ev: KVEvent) -> None:
        """A peer's lease-attached liveness key changed. DELETE (lease
        expiry = crash/partition, or clean shutdown) tears down our
        routes toward it; PUT (node back) reinstalls them."""
        try:
            node_id = int(ev.key[len(node_id_mod.LIVENESS_PREFIX):])
        except ValueError:
            return
        if node_id == self.node_id:
            return
        if ev.op == Op.PUT:
            self._apply_node(node_id, ev.value or {})
        else:
            self._remove_node(node_id)

    def _apply_node(self, node_id: int, info: dict) -> None:
        """Install routes to another node's pod + vpp/host subnets over
        the uplink. Mesh mode (resolver set): on-mesh peers route into
        the ICI fabric (node_id = mesh position, no encapsulation) and
        only off-mesh peers get VXLAN edge routes; otherwise every peer
        is a VXLAN peer (the reference's full-mesh,
        node_events.go:184-250)."""
        if node_id == self.node_id or not isinstance(info, dict):
            return
        peer_vtep = int(self.ipam.vxlan_ip_address(node_id))
        if self._peer_routes.get(node_id) == peer_vtep:
            return  # already installed (IP update without vtep change)
        mesh_pos = -1
        if self.mesh_node_resolver is not None:
            mesh_pos = int(self.mesh_node_resolver(node_id))
        if mesh_pos >= 0:
            # fabric peer: the cluster step's all_to_all row IS the
            # tunnel; next_hop=0 keeps the host VXLAN encap path (which
            # selects on REMOTE & next_hop != 0) off these packets
            with_hop = dict(
                tx_if=self.uplink_if,
                disposition=Disposition.REMOTE,
                next_hop=0,
                node_id=mesh_pos,
            )
        else:
            with_hop = dict(
                tx_if=self.uplink_if,
                disposition=Disposition.REMOTE,
                next_hop=peer_vtep,
                # mesh mode must mark edge peers -1 (a raw allocator id
                # would alias a fabric row); standalone mode keeps the
                # allocator id as observability metadata
                node_id=-1 if self.mesh_node_resolver is not None else node_id,
            )
        with self.dataplane.commit_lock:
            self.dataplane.builder.txn_label = f"node-event add {node_id}"
            self.dataplane.builder.add_route(
                str(self.ipam.other_node_pod_network(node_id)), **with_hop
            )
            self.dataplane.builder.add_route(
                str(self.ipam.other_node_vpp_host_network(node_id)), **with_hop
            )
            self.dataplane.swap()
        self._peer_routes[node_id] = peer_vtep
        log.info(
            "node %d added: %s", node_id,
            f"fabric row {mesh_pos}" if mesh_pos >= 0
            else f"routes via vtep {peer_vtep}",
        )

    def _remove_node(self, node_id: int) -> None:
        if self._peer_routes.pop(node_id, None) is None:
            return
        with self.dataplane.commit_lock:
            self.dataplane.builder.txn_label = f"node-event del {node_id}"
            self.dataplane.builder.del_route(
                str(self.ipam.other_node_pod_network(node_id))
            )
            self.dataplane.builder.del_route(
                str(self.ipam.other_node_vpp_host_network(node_id))
            )
            self.dataplane.swap()
        log.info("node %d removed", node_id)

    def _on_pod_event(self, ev: KVEvent) -> None:
        try:
            if ev.op == Op.PUT:
                self.policy_cache.update_pod(m.Pod.from_dict(ev.value))
            else:
                k = m.parse_key(_ksr_key(ev.key))
                self.policy_cache.delete_pod(
                    PodID(k.get("namespace", "default"), k["name"])
                )
        except Exception:
            log.exception("pod event failed: %s", ev.key)
            self._report_policy(PluginState.ERROR, f"pod event {ev.key}")

    def _on_policy_event(self, ev: KVEvent) -> None:
        try:
            if ev.op == Op.PUT:
                self.policy_cache.update_policy(m.Policy.from_dict(ev.value))
            else:
                k = m.parse_key(_ksr_key(ev.key))
                self.policy_cache.delete_policy(
                    k.get("namespace", "default"), k["name"]
                )
        except Exception:
            log.exception("policy event failed: %s", ev.key)
            self._report_policy(PluginState.ERROR, f"policy event {ev.key}")

    def _on_namespace_event(self, ev: KVEvent) -> None:
        try:
            if ev.op == Op.PUT:
                self.policy_cache.update_namespace(
                    m.Namespace.from_dict(ev.value)
                )
            else:
                k = m.parse_key(_ksr_key(ev.key))
                self.policy_cache.delete_namespace(k["name"])
        except Exception:
            log.exception("namespace event failed: %s", ev.key)
            self._report_policy(PluginState.ERROR, f"namespace event {ev.key}")

    def _on_service_event(self, ev: KVEvent) -> None:
        try:
            if ev.op == Op.PUT:
                self.service_processor.update_service(
                    m.Service.from_dict(ev.value)
                )
            else:
                k = m.parse_key(_ksr_key(ev.key))
                self.service_processor.delete_service(
                    k.get("namespace", "default"), k["name"]
                )
        except Exception:
            log.exception("service event failed: %s", ev.key)
            self._report_service(PluginState.ERROR, f"service event {ev.key}")

    def _on_endpoints_event(self, ev: KVEvent) -> None:
        try:
            if ev.op == Op.PUT:
                self.service_processor.update_endpoints(
                    m.Endpoints.from_dict(ev.value)
                )
            else:
                k = m.parse_key(_ksr_key(ev.key))
                self.service_processor.delete_endpoints(
                    k.get("namespace", "default"), k["name"]
                )
        except Exception:
            log.exception("endpoints event failed: %s", ev.key)
            self._report_service(PluginState.ERROR, f"endpoints event {ev.key}")


def main(argv=None) -> int:
    """contiv-agent main: config flag, event loop, SIGTERM close."""
    import argparse

    from vpp_tpu.cmd.config import load_config

    parser = argparse.ArgumentParser(prog="vpp-tpu-agent")
    parser.add_argument("--config", default=None, help="agent YAML config")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    agent = ContivAgent(load_config(args.config))
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    agent.start()
    log.info("agent up: node %s id %d", agent.config.node_name, agent.node_id)
    stop.wait()
    log.info("shutting down")
    agent.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
