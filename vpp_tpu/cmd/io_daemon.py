"""vpp-tpu-io: the packet-IO daemon process.

Owns the node's packet endpoints (AF_PACKET uplink, TAP devices for
pods, inherited socketpair fds for tests) and pumps frames between them
and the agent's shared-memory rings. The process-split analog of VPP
running beside the contiv-agent in the vswitch pod
(/root/reference/docker/vpp-vswitch/supervisord.conf:18-22).

Interface spec syntax (repeatable --if):
  --if 3:afpacket:eth0       AF_PACKET bound to eth0 as if-index 3
  --if 5:tap:pod-abc         TAP device pod-abc as if-index 5
  --if 4:fd:17               inherited socketpair/tun fd 17 as if-index 4
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from vpp_tpu.io.control import IOControlServer
from vpp_tpu.io.daemon import IODaemon
from vpp_tpu.io.rings import IORingPair
from vpp_tpu.io.transport import make_transport

log = logging.getLogger("io_daemon")


def parse_if_spec(spec: str) -> tuple:
    idx, kind, arg = spec.split(":", 2)
    return int(idx), kind, arg


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vpp-tpu-io")
    parser.add_argument("--shm", required=True,
                        help="shared-memory name of the ring pair")
    parser.add_argument("--slots", type=int, default=64)
    parser.add_argument("--snap", type=int, default=2048)
    parser.add_argument("--if", dest="ifs", action="append", default=[],
                        help="IDX:KIND:ARG (afpacket|tap|fd)", metavar="SPEC")
    parser.add_argument("--uplink", type=int, required=True,
                        help="if-index of the uplink")
    parser.add_argument("--host-if", type=int, default=None)
    parser.add_argument("--vtep", type=int, default=0,
                        help="this node's VTEP IPv4 as uint32")
    parser.add_argument("--vni", type=int, default=10)
    parser.add_argument("--control", default=None, metavar="SOCK",
                        help="unix socket for runtime attach/detach "
                             "(the agent's CNI server drives this)")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level)

    rings = IORingPair(n_slots=args.slots, snap=args.snap,
                       shm_name=args.shm, create=False)
    transports = {}
    for spec in args.ifs:
        idx, kind, arg = parse_if_spec(spec)
        transports[idx] = make_transport(kind, arg)
        log.info("if %d: %s(%s)", idx, kind, arg)
    daemon = IODaemon(
        rings, transports, uplink_if=args.uplink, host_if=args.host_if,
        vtep_ip=args.vtep, vni=args.vni,
    ).start()
    control = None
    if args.control:
        control = IOControlServer(daemon, args.control).start()
        log.info("control socket at %s", args.control)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    if control is not None:
        control.close()
    daemon.stop()
    for t in daemon.transports.values():
        t.close()
    rings.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
