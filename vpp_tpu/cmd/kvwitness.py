"""vpp-tpu-kvwitness: the HA kvstore pair's quorum arbiter.

Third voter of the 2-replicas + arbiter construction
(kvstore/witness.py) that stands in for the raft quorum the reference
gets from etcd (/root/reference/k8s/contiv-vpp.yaml:72-114). Holds no
cluster data — only the fencing epoch and the current primary's lease —
so it runs anywhere a few KB and a TCP port exist (the chart schedules
it on a third node, k8s/chart/).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading

from vpp_tpu.kvstore.witness import QuorumWitness


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="vpp-tpu kvstore quorum witness")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=12380)
    parser.add_argument("--persist", default=None,
                        help="epoch/primary survive restarts here "
                             "(atomic-rename JSON)")
    parser.add_argument("--port-file", default=None,
                        help="write the bound port here once listening")
    parser.add_argument("--status", default=None, metavar="HOST:PORT",
                        help="query a RUNNING witness and print its "
                             "arbitration state (epoch, primary, lease "
                             "remaining) — the operator one-liner for "
                             "'who is writable right now'")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)

    if args.status:
        from vpp_tpu.kvstore.witness import (
            WitnessClient, WitnessUnreachable,
        )

        try:
            st = WitnessClient(args.status).status()
        except (WitnessUnreachable, ValueError) as exc:
            # ValueError: malformed host:port — same operator-facing
            # one-liner, not a traceback
            print(f"witness {args.status} unreachable: {exc}")
            return 1
        print(f"epoch {st['epoch']}  primary {st['primary'] or '(none)'}"
              f"  lease remaining {st['remaining']:.1f}s")
        return 0

    logging.basicConfig(
        level=args.log_level,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    witness = QuorumWitness(host=args.host, port=args.port,
                            persist_path=args.persist)
    if args.port_file:
        import os

        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(witness.port))
        os.replace(tmp, args.port_file)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    witness.start()
    stop.wait()
    witness.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
