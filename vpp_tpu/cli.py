"""Debug CLI: the vppctl analog.

Reference: VPP's `vppctl` show commands (`show interface`, `show acl`,
`show session`, `show nat44`, `show ip fib`, `show trace`, `show run`,
`show errors`) used throughout docs/VPP_PACKET_TRACING_K8S.md. Operates
on a live Dataplane (and optionally its tracer/stats); every command
returns a string so it serves both the interactive REPL and tests.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.pipeline.tables import InterfaceType
from vpp_tpu.pipeline.vector import Disposition, ip4_str


class DebugCLI:
    def __init__(self, dataplane: Dataplane, tracer=None, stats=None,
                 pump=None, io_ctl=None, session_engine=None,
                 mesh_runtime=None, store=None, snapshotter=None,
                 ml_source=None, fleet=None, fleet_pump=None):
        self.dp = dataplane
        self.tracer = tracer
        self.stats = stats
        # optional IO front-end handles: the agent-side pump and the
        # control-socket client into the (separate) IO daemon process
        self.pump = pump
        self.io_ctl = io_ctl
        # optional host-stack handle (show session-rules)
        self.session_engine = session_engine
        # optional mesh/multi-host runtime handle (show mesh)
        self.mesh_runtime = mesh_runtime
        # optional cluster-store handle (show store: endpoint, fencing
        # epoch, HA failover state as this agent experiences it)
        self.store = store
        # optional SessionSnapshotter (show resilience: snapshot
        # generation/age, degraded components, backoff state)
        self.snapshotter = snapshotter
        # optional MlModelSource (show ml: load ledger, degraded flag)
        self.ml_source = ml_source
        # optional gateway-fleet handles (show fleet: ownership map,
        # epochs, migration/conservation counters — ISSUE 18)
        self.fleet = fleet
        self.fleet_pump = fleet_pump

    # --- dispatch ---
    def run(self, line: str) -> str:
        parts = line.strip().split()
        if not parts:
            return ""
        handlers = {
            ("show", "interface"): self.show_interface,
            ("show", "acl"): self.show_acl,
            ("show", "sessions"): self.show_sessions,
            ("show", "session"): self.show_session,
            ("show", "session-rules"): self.show_session_rules,
            ("show", "mesh"): self.show_mesh,
            ("show", "partitions"): self.show_partitions,
            ("show", "nat44"): self.show_nat44,
            ("show", "services"): self.show_services,
            ("show", "overlay"): self.show_overlay,
            ("show", "trace"): self.show_trace,
            ("show", "errors"): self.show_errors,
            ("show", "fastpath"): self.show_fastpath,
            ("show", "kernels"): self.show_kernels,
            ("show", "ml"): self.show_ml,
            ("show", "latency"): self.show_latency,
            ("show", "top-flows"): self.show_top_flows,
            ("show", "governor"): self.show_governor,
            ("show", "tenants"): self.show_tenants,
            ("show", "io"): self.show_io,
            ("show", "neighbors"): self.show_neighbors,
            ("show", "store"): self.show_store,
            ("show", "resilience"): self.show_resilience,
            ("show", "fleet"): self.show_fleet,
            ("help",): self.help,
        }
        for sig, fn in handlers.items():
            if tuple(parts[: len(sig)]) == sig:
                return fn()
        if tuple(parts[:2]) == ("show", "fib"):
            return self.show_fib(parts[2:])
        if tuple(parts[:2]) == ("show", "config-history"):
            return self.show_config_history(parts[2:])
        if tuple(parts[:2]) == ("show", "spans"):
            return self.show_spans(parts[2:])
        if tuple(parts[:2]) == ("test", "connectivity"):
            return self.test_connectivity(parts[2:])
        if tuple(parts[:2]) == ("trace", "add"):
            return self.trace_add(parts[2:])
        if tuple(parts[:2]) == ("trace", "clear"):
            return self.trace_clear()
        if tuple(parts[:2]) == ("config", "replay"):
            return self.config_replay(parts[2:])
        return f"unknown command: {line.strip()!r} (try 'help')"

    def help(self) -> str:
        return (
            "commands: show interface | show acl | show session | "
            "show sessions | show session-rules | show mesh | "
            "show partitions | "
            "show nat44 | show services | show overlay | "
            "show fib | show trace | show errors | "
            "show fastpath | show kernels | show ml | show latency | "
            "show top-flows | "
            "show governor | show tenants | show io | show neighbors | "
            "show store | "
            "show resilience | show fleet | "
            "show config-history [n] | show spans [n] | "
            "trace add [n] | trace clear | config replay <journal> | "
            "test connectivity <src> <dst> <tcp|udp|icmp> [dport]"
        )

    # --- config transaction trace (api-trace analog) ---
    def show_spans(self, args: List[str]) -> str:
        """Control-plane span timelines (trace/spans.py): per applied
        txn, the KSR → kvstore → agent → render → swap stage timings —
        the `show trace` analog for the config path."""
        from vpp_tpu.trace import spans as _spans

        try:
            limit = int(args[0]) if args else 10
            if limit <= 0:
                raise ValueError("count must be positive")
        except ValueError as e:
            return f"bad argument: {e}"
        return _spans.RECORDER.format_traces(limit=limit)

    def show_config_history(self, args: List[str]) -> str:
        """Tail of the NB transaction journal the live agent recorded
        (`api-trace` dump analog): epoch, timestamp, label, op count,
        and — when the epoch's swap was traced — the per-stage config
        path timings of the applying transaction."""
        journal = self.dp.journal
        if journal is None:
            return "config journal not enabled (set txn_journal_path)"
        limit = int(args[0]) if args else 20
        import os
        import time as _time

        from vpp_tpu.trace import spans as _spans

        if not journal.path or not os.path.exists(journal.path):
            return f"{journal.applied} txns applied (no journal file)"
        entries = journal.load_entries()
        # epoch -> per-stage seconds of the trace whose swap published it
        by_epoch = _spans.RECORDER.epoch_timings()
        lines = []
        for e in entries[-limit:]:
            ts = _time.strftime("%H:%M:%S", _time.localtime(e.get("t", 0)))
            label = e.get("label") or "-"
            line = (
                f"epoch {e.get('epoch'):>5}  {ts}  {len(e.get('ops', [])):>3} "
                f"ops  {label}"
            )
            _, stages = by_epoch.get(e.get("epoch"), (None, None))
            if stages:
                line += "  [" + " ".join(
                    f"{stage} {sec * 1e3:.2f}ms"
                    for stage, sec in sorted(stages.items())
                ) + "]"
            lines.append(line)
        lines.append(f"{len(entries)} txns journaled, showing last "
                     f"{min(limit, len(entries))}")
        if journal.torn_lines:
            lines.append(
                f"WARNING: {journal.torn_lines} torn trailing line "
                f"(crash mid-append) tolerated on load"
            )
        return "\n".join(lines)

    def config_replay(self, args: List[str]) -> str:
        """Replay a journal file against the LIVE dataplane as ONE
        transaction (bulk restore: stage every journaled op + a single
        epoch swap)."""
        if not args:
            return "usage: config replay <journal.jsonl>"
        from vpp_tpu.pipeline.txn import TxnJournal

        journal = TxnJournal(args[0])
        txns = journal.load()
        if not txns:
            return f"no transactions in {args[0]}"
        dp = self.dp
        with dp.commit_lock:
            snap = dp.builder.state_snapshot()
            try:
                for txn in txns:
                    txn.apply_to_builder(dp.builder)
            except Exception as e:  # noqa: BLE001 — debug path
                dp.builder.state_restore(snap)
                return f"replay failed (rolled back): {type(e).__name__}: {e}"
            dp.builder.txn_label = f"config-replay {args[0]}"
            epoch = dp.swap()
        return f"replayed {len(txns)} txns from {args[0]} -> epoch {epoch}"

    # --- commands ---
    def show_interface(self) -> str:
        dp = self.dp
        t = np.asarray(dp.builder.if_type)
        lines = [f"{'idx':>4} {'type':<8} {'acl-table':>9}  pod"]
        for i in np.nonzero(t != 0)[0]:
            i = int(i)
            pod = dp.if_pod.get(i)
            name = f"{pod[0]}/{pod[1]}" if pod else (
                "uplink" if i == dp.uplink_if else
                "host" if i == dp.host_if else ""
            )
            slot = int(dp.builder.if_local_table[i])
            lines.append(
                f"{i:>4} {InterfaceType(int(t[i])).name.lower():<8} "
                f"{slot if slot >= 0 else '-':>9}  {name}"
            )
        return "\n".join(lines)

    def show_acl(self) -> str:
        dp = self.dp
        b = dp.builder
        impl = getattr(dp, "classifier_impl", "dense")
        knob = getattr(dp, "classifier", "auto")
        lines = [
            f"classifier: {impl} (knob {knob}), "
            f"global rules {int(b.glb_nrules)}",
        ]
        if getattr(b, "bv_enabled", False):
            from vpp_tpu.ops.acl_bv import bv_global_bytes

            detail = (
                f"  bv: bitmap {bv_global_bytes(dp.config.max_global_rules)}"
                f" bytes, build {b.bv_build_ms:.2f} ms"
            )
            rebuilt = getattr(b, "bv_rebuilt", ())
            if rebuilt:
                detail += f", last rebuilt planes: {','.join(rebuilt)}"
            if not b.bv_ok():
                detail += " (NOT eligible: non-prefix mask rule)"
            lines.append(detail)
        ns = getattr(dp, "classify_ns_pkt", None)
        if ns is not None:
            lines.append(f"  classify probe: {ns:.1f} ns/pkt "
                         f"(time_classifier diagnostic)")
        for table_id, slot in sorted(dp.table_slots.items()):
            n = int(dp.builder.acl_nrules[slot])
            lines.append(f"local table {table_id} (slot {slot}, {n} rules):")
            lines.extend(self._rules(dp.builder.acl, slot, n))
        n = int(dp.builder.glb_nrules)
        lines.append(f"global table ({n} rules):")
        lines.extend(self._rules(dp.builder.glb, None, n))
        return "\n".join(lines)

    def _rules(self, packed, slot: Optional[int], n: int) -> List[str]:
        def col(name, i):
            a = packed[name]
            return a[slot][i] if slot is not None else a[i]

        out = []
        for i in range(n):
            act = "permit" if int(col("action", i)) == 1 else "deny"
            proto = int(col("proto", i))
            pstr = {6: "tcp", 17: "udp", 1: "icmp", -1: "any"}.get(proto, str(proto))
            src = f"{ip4_str(int(col('src_net', i)))}/{bin(int(col('src_mask', i))).count('1')}"
            dst = f"{ip4_str(int(col('dst_net', i)))}/{bin(int(col('dst_mask', i))).count('1')}"
            def port_range(lo, hi):
                if (lo, hi) == (0, 65535):
                    return "any"
                return str(lo) if lo == hi else f"{lo}-{hi}"

            sport = port_range(int(col("sport_lo", i)), int(col("sport_hi", i)))
            dport = port_range(int(col("dport_lo", i)), int(col("dport_hi", i)))
            out.append(f"  [{i}] {act} {pstr} {src}:{sport} -> {dst}:{dport}")
        return out

    def show_session(self) -> str:
        t = self.dp.tables
        if t is None:
            return "no live tables"
        valid = np.asarray(t.sess_valid).reshape(-1)
        idxs = np.nonzero(valid)[0]
        lines = [f"{len(idxs)} established sessions "
                 f"({valid.shape[0]} slots)"]
        src = np.asarray(t.sess_src).reshape(-1)
        dst = np.asarray(t.sess_dst).reshape(-1)
        ports = np.asarray(t.sess_ports).reshape(-1)
        proto = np.asarray(t.sess_proto).reshape(-1)
        age = np.asarray(t.sess_time).reshape(-1)
        for i in idxs[:64]:
            i = int(i)
            lines.append(
                f"  {ip4_str(int(src[i]))}:{int(ports[i]) >> 16} -> "
                f"{ip4_str(int(dst[i]))}:{int(ports[i]) & 0xFFFF} "
                f"proto {int(proto[i])} last-hit {int(age[i])}"
            )
        if len(idxs) > 64:
            lines.append(f"  ... {len(idxs) - 64} more")
        return "\n".join(lines)

    def show_sessions(self) -> str:
        """Session-TABLE health page (the per-flow dump is
        `show session`): geometry, live occupancy / load factor and the
        amortized sweep cursors of both set-associative tables
        (ops/session.py; docs/SESSIONS.md)."""
        t = self.dp.tables
        if t is None:
            return "no live tables"
        now = max(self.dp._now, self.dp.clock_ticks())
        max_age = int(np.asarray(t.sess_max_age))
        lines = [f"session tables (max_age {max_age} ticks, "
                 f"sweep stride {self.dp._sweep_stride} buckets/step)"]
        import jax.numpy as jnp

        for name, prefix, cursor in (
            ("reflective", "sess", t.sess_sweep_cursor),
            ("nat", "natsess", t.natsess_sweep_cursor),
        ):
            valid = getattr(t, f"{prefix}_valid")
            tme = getattr(t, f"{prefix}_time")
            n_buckets, ways = valid.shape
            slots = n_buckets * ways
            # aggregate ON DEVICE: at the 10M-slot config the valid +
            # time columns are ~270 MB across both tables — a CLI page
            # must pull back four scalars, not the arrays
            occ_m = valid == 1
            occupied = int(jnp.sum(occ_m))
            live = int(jnp.sum(occ_m & (now - tme <= max_age)))
            full = int(jnp.sum(jnp.sum(occ_m, axis=1) == ways))
            lines.append(
                f"  {name}: {slots} slots = {n_buckets} buckets x "
                f"{ways} ways")
            lines.append(
                f"    live {live} ({100.0 * live / slots:.1f}% load)  "
                f"occupied {occupied} (incl. expired)  "
                f"full-buckets {full}")
            lines.append(
                f"    sweep cursor {int(np.asarray(cursor))}/{n_buckets}")
        if self.stats is not None:
            tot = self.stats.totals_snapshot()
            lines.append(
                "  insert-fail {s}/{n} (sess/nat)  evictions "
                "expired {ee}+{ne} victim {ev}+{nv}".format(
                    s=tot.get("sess_insert_fail", 0),
                    n=tot.get("natsess_insert_fail", 0),
                    ee=tot.get("sess_evict_expired", 0),
                    ne=tot.get("natsess_evict_expired", 0),
                    ev=tot.get("sess_evict_victim", 0),
                    nv=tot.get("natsess_evict_victim", 0)))
        return "\n".join(lines)

    def show_mesh(self) -> str:
        """Mesh/multi-host runtime state: nodes this host drives, the
        lockstep tick/epoch counters (multi-host), fabric pump
        counters. The `show version`-grade operator one-pager for the
        multi-chip plane."""
        rt = self.mesh_runtime
        if rt is None:
            return "not a mesh agent (no runtime attached)"
        lines = []
        cluster = getattr(rt, "cluster", None)
        if cluster is not None:
            lines.append(
                f"cluster: {cluster.n_nodes} nodes, epoch {cluster.epoch}")
        local = getattr(cluster, "local_nodes", None)
        if local is not None:
            lines.append(f"local mesh rows: {local}")
        driver = getattr(rt, "driver", None)
        if driver is not None:
            lines.append(
                f"lockstep: tick {driver.ticks}, applied epoch-req "
                f"{driver.applied}, session aging every "
                f"{driver.expire_every} ticks")
        agents = getattr(rt, "agents", None)
        if agents:
            lines.append("agents: " + ", ".join(
                f"{a.config.node_name}(id {a.node_id})" for a in agents))
        pump = getattr(rt, "cluster_pump", None)
        if pump is not None:
            ps = pump.stats
            lines.append(
                f"fabric pump: steps {ps.get('steps', 0)}, frames "
                f"{ps.get('frames', 0)}, fabric pkts "
                f"{ps.get('fabric_pkts', 0)}, tx-ring-full "
                f"{ps.get('tx_ring_full', 0)}, errors "
                f"{ps.get('batch_errors', 0)}, pending "
                f"{pump.has_pending() if hasattr(pump, 'has_pending') else '?'}")
        return "\n".join(lines) or "mesh runtime attached, no state"

    def show_partitions(self) -> str:
        """The partition-rule layer's resolved placements (ISSUE 12):
        every DataplaneTables field's spec, the rule that assigned it,
        and — on a mesh — the live selection gates plus per-shard
        session residency. The operator answer to "what actually
        shards, and why"."""
        from vpp_tpu.parallel.partition import RULE_AXIS, spec_manifest

        rt = self.mesh_runtime
        cluster = getattr(rt, "cluster", None) if rt is not None else None
        lines = []
        if cluster is None:
            lines.append("standalone dataplane (no mesh attached); "
                         "canonical placements:")
            shards = 1
            eff = None
        else:
            shards = int(getattr(cluster, "rule_shards", 1))
            lines.append(
                f"mesh: {cluster.n_nodes} nodes x {shards} rule "
                f"shards, epoch {cluster.epoch}")
            lines.append(
                "selection: classifier="
                f"{getattr(cluster, 'classifier_impl', '?')} "
                f"fastpath={getattr(cluster, 'fastpath_selected', '?')} "
                f"ml={getattr(cluster, 'ml_selected', '?')}")
            eff = getattr(cluster, "_shardings", None)
        by_rule = {}
        for f, entry in spec_manifest().items():
            spec = (getattr(eff, f).spec if eff is not None
                    else entry.spec)
            axes = tuple(a for a in spec if a is not None)
            key = (RULE_AXIS if RULE_AXIS in axes else "replicated",
                   entry.pattern, entry.reason)
            by_rule.setdefault(key, []).append(f)
        for (axis, pattern, reason), fields in by_rule.items():
            lines.append(f"  [{axis:>10}] {pattern}  ({len(fields)} "
                         f"fields) — {reason}")
        if cluster is not None and cluster.tables is not None:
            resident = cluster.shard_sessions_resident()
            lines.append("per-shard sessions resident: " + ", ".join(
                f"shard {s}: {resident[s]}" for s in range(shards)))
        return "\n".join(lines)

    def show_session_rules(self) -> str:
        """The `show session rules` analog: the VPPTCP renderer's
        installed session filter tables, most-specific first per scope
        (reference: session_rules_table dump,
        plugins/policy/renderer/vpptcp/bin_api/session)."""
        eng = self.session_engine
        if eng is None:
            return "no session rule engine attached"
        rules = eng.dump()
        lines = [f"{len(rules)} session rules "
                 f"(capacity {eng.capacity})"]
        scope_name = {1: "LOCAL", 2: "GLOBAL"}
        act_name = {0: "deny", 1: "allow"}
        for r in rules[:128]:
            lcl = (f"{ip4_str(int(r.lcl_net))}/{r.lcl_plen}"
                   if r.lcl_plen else "any")
            rmt = (f"{ip4_str(int(r.rmt_net))}/{r.rmt_plen}"
                   if r.rmt_plen else "any")
            ns = "" if r.appns_index < 0 else f" ns {r.appns_index}"
            lines.append(
                f"  {scope_name.get(r.scope, r.scope)}{ns} "
                f"proto {r.transport_proto} "
                f"lcl {lcl}:{r.lcl_port or 'any'} "
                f"rmt {rmt}:{r.rmt_port or 'any'} "
                f"-> {act_name.get(r.action, r.action)}"
            )
        if len(rules) > 128:
            lines.append(f"  ... {len(rules) - 128} more")
        return "\n".join(lines)

    def show_nat44(self) -> str:
        dp = self.dp
        b = dp.builder
        lines = ["static mappings:"]
        for s in np.nonzero(np.asarray(b.nat_bcnt) > 0)[0]:
            s = int(s)
            boff, bcnt = int(b.nat_boff[s]), int(b.nat_bcnt[s])
            lines.append(
                f"  {ip4_str(int(b.nat_ext_ip[s]))}:{int(b.nat_ext_port[s])} "
                f"proto {int(b.nat_proto[s])} -> {bcnt} backends:"
            )
            prev = 0
            for j in range(boff, boff + bcnt):
                w = int(b.natb_cumw[j]) - prev
                prev = int(b.natb_cumw[j])
                lines.append(
                    f"    {ip4_str(int(b.natb_ip[j]))}:{int(b.natb_port[j])} "
                    f"weight {w}"
                )
        t = dp.tables
        if t is not None:
            n = int(np.asarray(t.natsess_valid).sum())
            lines.append(f"nat sessions: {n}")
        return "\n".join(lines)

    def show_services(self) -> str:
        """The svc-plane registry (ISSUE 19): per-VIP backend sets
        with their sticky hash-way spread, plus the last svc-group
        upload record — the churn blob `overlay_bench` prices as
        svc_churn_bytes."""
        from vpp_tpu.pipeline.tables import svc_capacity

        b = self.dp.builder
        V, B = svc_capacity(b.config)
        if V <= 0:
            return "svc planes off (dataplane.svc_vips is 0)"
        lines = [f"services: {len(b.services)}/{V} VIPs, "
                 f"{B} backend ways each"]
        for key in sorted(b.services):
            e = b.services[key]
            ways: dict = {}
            for m in e["assign"]:
                ways[(m[0], m[1])] = ways.get((m[0], m[1]), 0) + 1
            snat = " self-snat" if e["self_snat"] else ""
            lines.append(
                f"  {ip4_str(key[0])}:{key[1]} proto {key[2]} -> "
                f"{len(e['members'])} backends{snat}:")
            for bip, bport, w in e["members"]:
                lines.append(
                    f"    {ip4_str(bip)}:{bport} weight {w} "
                    f"ways {ways.get((bip, bport), 0)}/{B}")
        up = b.svc_upload
        if up:
            lines.append(
                "last churn: {:.2f} ms, {} B ({} fields + {} B "
                "scatter blob)".format(
                    float(up.get("ms", 0.0)), int(up.get("bytes", 0)),
                    len(up.get("fields", ())),
                    int(up.get("blob_bytes", 0))))
        return "\n".join(lines)

    def show_overlay(self) -> str:
        """Overlay state (ISSUE 19): the step-form knob, this node's
        VTEP, the on-device VNI -> tenant admission map, and the
        overlay stage counters when a collector is attached."""
        dp = self.dp
        knob = getattr(dp.config, "overlay", "off")
        lines = [f"overlay: {knob}"]
        vtep = getattr(dp, "_vtep", None)
        lines.append("vtep: " + (ip4_str(int(vtep)) if vtep is not None
                                 else "(unset)"))
        vni = np.asarray(dp.builder.tnt["tnt_vni"])
        rows = [(int(t), int(v)) for t, v in enumerate(vni) if v >= 0]
        if rows:
            lines.append("vni -> tenant admission map:")
            for t, v in rows:
                lines.append(f"  vni {v} -> tenant {t}")
        else:
            lines.append("vni admission map: empty (all decap "
                         "fails closed)")
        if self.stats is not None:
            totals = self.stats.totals_snapshot()
            for k in ("ovl_decap", "ovl_encap", "drop_overlay"):
                lines.append(f"{k:<14} {totals.get(k, 0):>12}")
        return "\n".join(lines)

    # route rows rendered without a prefix filter before the page
    # demands one — a 1M-route FIB must never be formatted slot by
    # slot in Python (the ISSUE 15 satellite; `show fib <prefix>`
    # narrows)
    FIB_LIST_MAX = 256

    def show_fib(self, args: Optional[List[str]] = None) -> str:
        """Summary-first FIB page (ISSUE 15): impl/ladder state, route
        histogram by prefix length, ECMP groups with per-member
        forwarded packets, plane bytes and the last churn upload —
        host scalars, no per-slot Python loop. Full route rows render
        only for small tables or under a prefix filter
        (``show fib <prefix[/len]>``: routes covering or covered by
        it), matched with one vectorized NumPy pass."""
        dp = self.dp
        b = dp.builder
        snap = dp.fib_snapshot()
        by_len = " ".join(f"/{L}:{n}"
                          for L, n in sorted(snap["by_length"].items()))
        lines = [
            "FIB: impl {} (knob {}{}), routes {}, plane bytes {}".format(
                snap["impl"], snap["knob"],
                "" if snap["lpm_ok"] else ", lpm ineligible",
                snap["routes"], snap["plane_bytes"]),
            f"routes by length: {by_len or '(none)'}",
        ]
        up = snap.get("upload") or {}
        if up:
            lines.append(
                "last churn: {:.2f} ms, {} B ({} fields + {} B "
                "slot blob)".format(
                    float(up.get("ms", 0.0)), int(up.get("bytes", 0)),
                    len(up.get("fields", ())),
                    int(up.get("blob_bytes", 0))))
        for gid, members in sorted(snap["ecmp_groups"].items()):
            lines.append(f"ecmp group {gid}: {len(members)} members")
            for m in members:
                lines.append(
                    f"  via {ip4_str(m['nh'])} if {m['tx_if']} "
                    f"node {m['node']} ways {len(m['ways'])} "
                    f"pkts {m['pkts']}")
        plen = np.asarray(b.fib_plen)
        live = plen >= 0
        want = None
        if args:
            try:
                import ipaddress as _ipaddress

                net = _ipaddress.ip_network(args[0], strict=False)
            except ValueError as e:
                return f"bad prefix filter: {e}"
            qlen = net.prefixlen
            qmask = np.uint32(
                ((1 << 32) - 1) ^ ((1 << (32 - qlen)) - 1) if qlen else 0)
            qnet = np.uint32(int(net.network_address)) & qmask
            pfx = np.asarray(b.fib_prefix)
            msk = np.asarray(b.fib_mask)
            # route covers the query, or the query covers the route —
            # one vectorized pass, never a per-slot Python loop
            covers = (qnet & msk) == pfx
            inside = (pfx & qmask) == qnet
            want = live & (covers | inside)
        elif int(live.sum()) <= self.FIB_LIST_MAX:
            want = live
        else:
            lines.append(
                f"({int(live.sum())} routes — pass a prefix filter: "
                f"show fib <prefix[/len]>)")
        if want is not None:
            rows = []
            idx = np.nonzero(want)[0]
            shown = idx[:self.FIB_LIST_MAX]
            for i in shown:
                i = int(i)
                disp = Disposition(int(b.fib_disp[i])).name.lower()
                extra = ""
                if int(b.fib_grp[i]) >= 0:
                    extra = f" ecmp-group {int(b.fib_grp[i])}"
                if int(b.fib_node_id[i]) >= 0:
                    extra += f" node {int(b.fib_node_id[i])}"
                if int(b.fib_next_hop[i]):
                    extra += f" via {ip4_str(int(b.fib_next_hop[i]))}"
                rows.append(
                    f"  {ip4_str(int(b.fib_prefix[i]))}/{int(plen[i])} "
                    f"-> if {int(b.fib_tx_if[i])} [{disp}]{extra}"
                )
            lines.extend(sorted(rows))
            if len(idx) > len(shown):
                lines.append(f"  ... {len(idx) - len(shown)} more "
                             f"(narrow the filter)")
        return "\n".join(lines)

    def _resolve_rx_if(self, src_ip: int):
        """Longest-prefix FIB match for ``src_ip`` with a LOCAL
        disposition → that pod's interface is where its traffic enters
        the vswitch (the reference's per-pod rx interface). One
        vectorized NumPy pass — the old per-slot Python loop walked
        every slot, unusable at the 1M-route regime (ISSUE 15)."""
        b = self.dp.builder
        plen = np.asarray(b.fib_plen)
        hit = ((np.uint32(src_ip) & np.asarray(b.fib_mask))
               == np.asarray(b.fib_prefix))
        cand = (plen >= 0) & hit & \
            (np.asarray(b.fib_disp) == int(Disposition.LOCAL))
        if not cand.any():
            return None
        best = int(np.argmax(np.where(cand, plen, -1)))
        return int(b.fib_tx_if[best])

    def test_connectivity(self, args: list) -> str:
        """One-shot connectivity probe — the robot-suite ping/TCP checks
        as a vppctl command: inject a synthetic packet, trace its path
        through the pipeline, report the verdict.

        usage: test connectivity <src-ip> <dst-ip> <tcp|udp|icmp> [dport]
        """
        from vpp_tpu.pipeline.vector import ip4, make_packet_vector
        from vpp_tpu.trace.tracer import PacketTracer

        if len(args) < 3:
            return ("usage: test connectivity <src-ip> <dst-ip> "
                    "<tcp|udp|icmp> [dport] [sport]")
        src_s, dst_s, proto_s = args[0], args[1], args[2]
        proto = {"tcp": 6, "udp": 17, "icmp": 1}.get(proto_s.lower())
        if proto is None:
            return f"unknown protocol {proto_s!r} (tcp|udp|icmp)"
        try:
            # strict validation: ip4() would silently wrap octets > 255
            # into neighboring octets and numpy columns overflow on
            # huge ints — a debug tool must reject typos, not probe a
            # different address and return a confident wrong verdict
            import ipaddress as _ipaddress

            _ipaddress.IPv4Address(src_s)
            _ipaddress.IPv4Address(dst_s)
            dport = int(args[3]) if len(args) > 3 else 80
            sport = int(args[4]) if len(args) > 4 else 40000
            if not (0 <= dport <= 65535 and 0 <= sport <= 65535):
                raise ValueError("port out of range 0-65535")
            src_int = ip4(src_s)
        except (ValueError, IndexError) as e:
            # operator typo must degrade to a message, never a
            # traceback out of run() (every command returns a string)
            return f"bad argument: {e}"
        rx_if = self._resolve_rx_if(src_int)
        if rx_if is None:
            return (f"no LOCAL route covers src {src_s} — the probe "
                    "must originate from a pod this node hosts")
        probe = make_packet_vector([{
            "src": src_s, "dst": dst_s, "proto": proto,
            "sport": sport, "dport": dport, "rx_if": rx_if,
        }])
        try:
            # side-effect-free: no session install, no tracer swap
            res = self.dp.probe(probe)
        except RuntimeError as e:  # e.g. cluster staging handle
            return f"probe unavailable: {e}"
        tracer = PacketTracer()
        tracer.add(1)
        tracer.record(res)
        disp = Disposition(int(np.asarray(res.disp)[0]))
        tx_if = int(np.asarray(res.tx_if)[0])
        verdict = {
            Disposition.LOCAL: f"FORWARDED -> if {tx_if}",
            Disposition.REMOTE: f"FORWARDED -> fabric (if {tx_if})",
            Disposition.HOST: "PUNTED to host stack",
            Disposition.DROP: "DROPPED",
        }.get(disp, disp.name)
        entries = tracer.entries()
        trace = entries[0].format() if entries else "(no trace captured)"
        return (f"{src_s} -> {dst_s} {proto_s}/{dport} via if {rx_if}\n"
                f"{trace}\nverdict: {verdict}")

    def show_fleet(self) -> str:
        """Gateway-fleet one-pager (ISSUE 18): instances, range
        ownership (with fenced ranges called out — those DROP until
        recovered), epoch high-water, migration totals and the
        conservation ledger the steering tier guarantees exactly."""
        fleet = self.fleet
        if fleet is None:
            return "fleet: not configured (single-instance gateway)"
        fs = fleet.stats_snapshot()
        lines = [
            f"fleet: {fs['instances']} instances, {fs['ranges']} "
            f"hash ranges, epoch high-water {fs['epoch_max']}",
        ]
        by_inst: dict = {}
        for rid, owner in sorted(fs["owners"].items()):
            by_inst.setdefault(owner, []).append(rid)
        for inst in sorted(by_inst):
            rids = by_inst[inst]
            lines.append(
                f"  {inst}: {len(rids)} ranges "
                f"({', '.join(str(r) for r in rids[:12])}"
                f"{', ...' if len(rids) > 12 else ''}), "
                f"steered {fs['steered'].get(inst, 0)}")
        if fs["fenced_ranges"]:
            lines.append(
                f"  FENCED: {fs['fenced_ranges']} ranges mid-migration "
                f"(traffic drops attributed; run recover)")
        lines.append(
            f"migrations: {fs['migrated_ranges']} ranges / "
            f"{fs['migrated_sessions']} sessions across "
            f"{fs['rebalances']} rebalances "
            f"({fs['recovered_ranges']} crash-recovered)")
        offered, accounted = fleet.conservation()
        lines.append(
            f"conservation: offered {offered} == steered "
            f"{sum(fs['steered'].values())} + fenced "
            f"{fs['fenced_drops']} + no-owner {fs['no_owner_drops']}"
            f" -> {'EXACT' if offered == accounted else 'VIOLATED'}")
        if self.fleet_pump is not None:
            ps = self.fleet_pump.stats_snapshot()
            lines.append(
                f"pump: delivered {sum(ps['delivered'].values())}, "
                f"queue drops {sum(ps['queue_drops'].values())}, "
                f"pending {self.fleet_pump.pending()}")
            for inst, aux in sorted(ps["aux"].items()):
                rx = aux.get("rx", 0)
                hits = aux.get("sess_hits", 0)
                lines.append(
                    f"  {inst}: rx {rx}, session hits {hits} "
                    f"({100.0 * hits / rx if rx else 0.0:.1f}%)")
        return "\n".join(lines)

    def show_resilience(self) -> str:
        """Crash-consistency + degraded-mode one-pager (ISSUE 8): the
        snapshot generation/age, which components are degraded, and
        the live reconnect backoff state — the operator's first stop
        after an incident ('did the table survive, and what are we
        running without right now?')."""
        lines = []
        # degraded components (mirrors vpp_tpu_degraded{component=})
        store = self.store
        kv_deg = bool(getattr(store, "degraded", False))
        ring_deg = bool(getattr(self.pump, "degraded_ring", False))
        snap = self.snapshotter
        snap_deg = bool(getattr(snap, "degraded", False))
        flags = []
        if kv_deg:
            stale = store.staleness_s() if hasattr(store, "staleness_s") \
                else 0.0
            flags.append(f"kvstore (serving last-adopted epoch, "
                         f"stale {stale:.1f}s)")
        if ring_deg:
            flags.append("ring (persistent pump fell back to dispatch "
                         "mode)")
        if snap_deg:
            flags.append("snapshot (last attempt failed)")
        lines.append("degraded: " + (", ".join(flags) if flags
                                     else "none"))
        if kv_deg and hasattr(store, "backoff_state"):
            bo = store.backoff_state()
            if bo:
                lines.append(
                    f"kvstore reconnect backoff: attempt "
                    f"{bo.get('attempt', 0)}, last delay "
                    f"{bo.get('last_delay_s', 0.0)}s "
                    f"(base {bo.get('base_s', 0.0)}s, cap "
                    f"{bo.get('cap_s', 0.0)}s)")
        if ring_deg and self.pump is not None:
            lines.append(
                f"ring faults: "
                f"{getattr(self.pump, '_ring_faults', 0)} "
                f"(limit {getattr(self.pump, 'ring_fault_limit', 0)})")
        if snap is None:
            lines.append("snapshot: not configured")
            return "\n".join(lines)
        s = snap.stats_snapshot()
        age = s["age_s"]
        lines.append(
            f"snapshot: generation {s['generation']}, "
            f"age {'-' if age < 0 else f'{age:.1f}s'}, "
            f"{s['snapshots']} published, "
            f"{s['snapshot_failures']} failed")
        lines.append(
            f"snapshot chunks: {s['chunks_written']} written "
            f"({s['bytes_written']} bytes, "
            f"{s['chunk_seconds']:.3f}s), "
            f"{s['chunks_skipped']} skipped clean")
        restores = {k: v for k, v in s["restores"].items() if v}
        lines.append(
            "restores: " + (", ".join(f"{k} {v}" for k, v in
                                      sorted(restores.items()))
                            if restores else "none attempted"))
        if s["last_error"]:
            lines.append(f"last error: {s['last_error']}")
        return "\n".join(lines)

    def show_store(self) -> str:
        """Cluster-store health as THIS agent experiences it: which
        endpoint it is on, the fencing epoch its writes carry, and the
        failover candidates (the etcdctl endpoint-status analog for
        the fenced HA pair, kvstore/witness.py)."""
        store = self.store
        if store is None:
            return "no store handle attached"
        import time as _time

        lines = []
        if hasattr(store, "endpoints"):  # RemoteKVStore
            t0 = _time.perf_counter()
            up = True
            try:
                store.ping()
                rtt = f"{(_time.perf_counter() - t0) * 1e3:.1f} ms"
            except Exception as e:  # noqa: BLE001 — debug path
                up = False
                rtt = f"UNREACHABLE ({type(e).__name__})"
            lines.append(f"connected: {store.host}:{store.port}  "
                         f"ping {rtt}")
            for host, port in store.endpoints:
                mark = " *" if (host, port) == (store.host,
                                                store.port) else ""
                lines.append(f"  endpoint {host}:{port}{mark}")
            epoch = store.fencing_epoch
            # None is ambiguous by design: a pre-fencing server never
            # answers the epoch op, AND a client mid-failover has
            # nulled it until the new primary answers — don't let the
            # label misdiagnose the exact window this command debugs
            lines.append(
                f"fencing epoch: "
                f"{'unknown (pre-fencing server, or refresh pending after failover)' if epoch is None else epoch}"
            )
            if up:
                try:
                    lines.append(f"revision: {store.revision}")
                except Exception as e:  # noqa: BLE001 — debug path
                    lines.append(
                        f"revision: unavailable ({type(e).__name__})")
            else:
                # the ping already burned its timeout; a second doomed
                # request would double the operator's stall
                lines.append("revision: unavailable (server down)")
        else:  # in-process KVStore
            lines.append("in-process store (no HA pair)")
            lines.append(f"revision: {store.revision}, "
                         f"fencing epoch: {store.fencing_epoch}, "
                         f"keys: {len(store.list_keys(''))}")
        return "\n".join(lines)

    def show_kernels(self) -> str:
        """Per-op kernel rung selection (ISSUE 16): for each
        gather-bound hot op — classifier, fib, session — the knob the
        operator set, the rung the ladder selected, and WHY (backend
        gate, structure gate, explicit knob). The operator view of
        Dataplane.kernel_snapshot(), twinned with the
        vpp_tpu_kernel_impl info gauge family."""
        snap_fn = getattr(self.dp, "kernel_snapshot", None)
        if not callable(snap_fn):
            return "kernels: no dataplane kernel snapshot available"
        snap = snap_fn()
        lines = [
            "kernel implementation ladders "
            f"(backend: {snap['backend']}, pallas "
            f"{'available' if snap['pallas_available'] else 'unavailable'}):",
            f"  {'op':<12} {'knob':<8} {'selected':<9} why",
        ]
        for op in ("classifier", "fib", "session"):
            s = snap[op]
            lines.append(
                f"  {op:<12} {s['knob']:<8} {s['impl']:<9} {s['why']}")
        return "\n".join(lines)

    def show_fastpath(self) -> str:
        """Two-tier dispatch state (pipeline/graph.py): whether the
        classify-free established-flow kernel is engaged, the gating
        knobs, and how much traffic actually rides it — the `show
        acl-plugin sessions`-grade operator view of the fast path."""
        dp = self.dp
        enabled = getattr(dp, "fastpath_enabled", False)
        engaged = getattr(dp, "_use_fastpath", False)
        min_rules = getattr(dp, "fastpath_min_rules", 0)
        lines = [
            "fastpath: {} (engaged: {})".format(
                "enabled" if enabled else "disabled",
                "yes" if engaged else
                f"no — global rules {dp.builder.glb_nrules} < "
                f"min-rules {min_rules}" if enabled else "no",
            ),
            f"  dispatch predicate: all valid packets hit a live "
            f"reflective session, none DNAT-matches",
            f"  global rules: {dp.builder.glb_nrules}, "
            f"min-rules threshold: {min_rules}",
        ]
        t = dp.tables
        if t is not None:
            # live = valid AND not idle-expired — what the dispatch
            # predicate's lookups actually see (an all-expired table
            # must not read as thousands of live sessions here)
            import jax.numpy as jnp

            now = max(getattr(dp, "_now", 0), dp.clock_ticks())
            # aggregate ON device (show_sessions rationale): the table
            # is [n_buckets, W] — slots = size, not the bucket count
            valid = t.sess_valid == 1
            fresh_mask = now - t.sess_time <= t.sess_max_age
            live = int(jnp.sum(valid & fresh_mask))  # transfer-ok: scalar
            nvalid = int(jnp.sum(valid))  # transfer-ok: scalar
            lines.append(
                f"  sessions: {live} live "
                f"of {t.sess_valid.size} slots "
                f"({nvalid} valid)"
            )
        if self.pump is not None:
            s = self.pump.stats
            total = int(s.get("batches", 0))
            fastb = int(s.get("fastpath_batches", 0))
            alive = int(s.get("fastpath_alive", 0))
            hits = int(s.get("fastpath_hits", 0))
            pct = 100.0 * hits / alive if alive else 0.0
            lines.append(
                f"  pump: {fastb}/{total} batches on the fast path, "
                f"session-hit {pct:.1f}% ({hits}/{alive} pkts)"
            )
        return "\n".join(lines)

    def show_ml(self) -> str:
        """Per-packet ML stage state (ISSUE 10; ops/mlscore.py): the
        configured knob vs the LIVE compiled mode, the staged model's
        geometry/thresholds/policy, the verdict counters, and the
        loader's refusal ledger — the `show acl`-grade operator page
        for the scoring stage."""
        dp = self.dp
        b = dp.builder
        knob = getattr(dp, "ml_stage", "off")
        mode = getattr(dp, "_ml_mode", "off")
        kind_code = int(getattr(b, "ml_kind", 0))
        kind = {0: "none", 1: "mlp", 2: "forest"}.get(kind_code, "?")
        lines = [
            f"ml stage: {mode} (knob {knob}, model {kind})",
        ]
        if kind_code:
            from vpp_tpu.ops.mlscore import ML_ACTION_NAMES

            ml = b.ml
            action = ML_ACTION_NAMES.get(
                int(ml["glb_ml_action"]), "?")
            lines.append(
                f"  model: v{int(ml['glb_ml_version'])}, flag-thresh "
                f"{int(ml['glb_ml_thresh'])}, action {action}"
                + (f" (admit 1/{1 << int(ml['glb_ml_rl_shift'])} "
                   f"flagged flows)" if action == "ratelimit" else ""))
            if kind_code == 1:
                f_dim, h = ml["glb_ml_w1"].shape
                lines.append(
                    f"  mlp: {f_dim} features x {h} hidden, requant "
                    f"shift {int(ml['glb_ml_s1'])}")
            else:
                t, d = ml["glb_ml_f_feat"].shape
                lines.append(
                    f"  forest: {t} trees x depth {d} "
                    f"({ml['glb_ml_f_leaf'].shape[1]} leaves)")
        else:
            lines.append("  no model staged (set ml_model_path, or "
                         "TableBuilder.set_ml_model)")
        if self.stats is not None:
            tot = self.stats.totals_snapshot()
            lines.append(
                f"  verdicts: scored {tot.get('ml_scored', 0)}, "
                f"flagged {tot.get('ml_flagged', 0)}, "
                f"drops {tot.get('ml_drops', 0)}")
        if self.pump is not None:
            s = self.pump.stats
            lines.append(
                f"  pump riders: scored {s.get('ml_scored', 0)}, "
                f"flagged {s.get('ml_flagged', 0)}, "
                f"drops {s.get('ml_drops', 0)}")
        src = self.ml_source
        if src is not None:
            st = src.stats_snapshot()
            outcomes = {k: v for k, v in st["outcomes"].items() if v}
            lines.append(
                f"  loader: {st['path']}, "
                + ("DEGRADED (previous model serving), "
                   if st["degraded"] else "")
                + ("loads " + ", ".join(
                    f"{k} {v}" for k, v in sorted(outcomes.items()))
                   if outcomes else "no loads attempted"))
            if st["last_error"]:
                lines.append(f"  last load error: {st['last_error']}")
        return "\n".join(lines)

    def _tel_snapshot(self):
        """Collect-facing telemetry snapshot: the pump's ring-rider
        copy when one exists (persistent mode — host scalars only,
        nothing crosses the device transport at render time), else the
        dataplane's small-plane fetch."""
        fn = getattr(self.pump, "tel_snapshot", None)
        snap = fn() if callable(fn) else None
        if snap is None:
            fn = getattr(self.dp, "telemetry_snapshot", None)
            snap = fn() if callable(fn) else None
        return snap

    def show_latency(self) -> str:
        """Device wire-latency page (ISSUE 11; ops/telemetry.py): the
        on-device log2 histogram of per-packet rx-enqueue → tx-append
        latency, with p50/p99/p99.9 derived host-side — the `show
        latency` every reflex-plane decision (ROADMAP item 3's
        governor) reads."""
        mode = getattr(self.dp, "_tel_mode", "off")
        if mode == "off":
            return ("telemetry off (set dataplane.telemetry: "
                    "latency | full)")
        snap = self._tel_snapshot()
        if snap is None:
            return f"telemetry {mode}: no samples yet"
        from vpp_tpu.ops.telemetry import quantiles_from_bins

        bins = np.asarray(snap["bins"], np.int64)
        total = int(bins.sum())
        lines = [f"wire latency (telemetry {mode}): {total} packets "
                 f"observed on device"]
        if total:
            p50, p99, p999 = quantiles_from_bins(bins)
            lines.append(
                f"  p50 {p50:.0f}us  p99 {p99:.0f}us  "
                f"p99.9 {p999:.0f}us")
            lines.append(f"  {'bucket':<16} {'count':>10}  share")
            for b, n in enumerate(bins):
                if not n:
                    continue
                lo = (1 << b) if b else 0
                hi = 1 << (b + 1)
                rng = (f"[{lo}us, {hi}us)" if b < len(bins) - 1
                       else f">= {lo}us")
                lines.append(
                    f"  {rng:<16} {int(n):>10}  "
                    f"{100.0 * int(n) / total:5.1f}%")
        return "\n".join(lines)

    def show_top_flows(self) -> str:
        """Heavy-hitter candidates of the device count-min flow sketch
        (ISSUE 11): the K elected flows with their estimated packet
        counts — the page that names the flows behind a latency spike
        or DDoS flag without ever shipping the session table."""
        mode = getattr(self.dp, "_tel_mode", "off")
        if mode != "full":
            return ("flow sketch off (set dataplane.telemetry: full)")
        snap = self._tel_snapshot()
        if snap is None:
            return "telemetry full: no samples yet"
        cnt = np.asarray(snap["top_cnt"], np.int64)
        order = np.argsort(-cnt)
        lines = [f"top flows ({int(snap['sketched'])} packets "
                 f"sketched; counts are count-min estimates — "
                 f"over-counting possible, never under)"]
        lines.append(f"  {'#':>2} {'flow':<44} {'est-pkts':>10}")
        shown = 0
        for k in order:
            k = int(k)
            if cnt[k] <= 0:
                continue
            ports = int(snap["top_ports"][k])
            flow = (f"{ip4_str(int(snap['top_src'][k]))}:{ports >> 16}"
                    f" -> {ip4_str(int(snap['top_dst'][k]))}"
                    f":{ports & 0xFFFF}")
            lines.append(f"  {shown:>2} {flow:<44} {int(cnt[k]):>10}")
            shown += 1
        if not shown:
            lines.append("  (no candidates elected yet)")
        return "\n".join(lines)

    def show_governor(self) -> str:
        """Reflex-plane latency governor state (ISSUE 13;
        io/governor.py): operating mode, the live window shape on the
        ladder, the last control observation, the priority lane's
        counters and the attributed overload shedding — all host
        scalars (the PR 6 rule: nothing crosses the device
        transport for a debug page)."""
        pump = self.pump
        gov = getattr(pump, "governor", None) if pump is not None \
            else None
        if gov is None:
            return ("no latency governor attached "
                    "(io.latency_slo_us = 0 — open-loop pump)")
        s = gov.snapshot()
        lines = [
            f"governor: mode {s['mode']}"
            + (" (WEDGED — window shape frozen)" if s['wedged'] else "")
            + (", shedding bulk" if s['shedding'] else ""),
            f"slo: {s['slo_us']:.0f}us, hysteresis band "
            f"[{s['slo_us'] * (1 - gov.hysteresis_pct / 100.0):.0f}, "
            f"{s['slo_us']:.0f}]us",
            f"window shape: level {s['level']}/{s['levels'] - 1}, "
            f"fill {s['fill']} slots, inflight {s['inflight']}",
            f"last observation: p99 {s['last_p99_us']:.0f}us, "
            f"queue-est {s['queue_est_us']:.0f}us "
            f"(t_svc {s['t_svc_us']:.0f}us/frame), "
            f"avg window fill {s['fill_avg']:.2f}",
            f"control loop: {s['ticks']} ticks "
            f"({s['tick_errors']} errors), steps "
            f"{s['adjust_down']} down / {s['adjust_up']} up, "
            f"transitions " + ", ".join(
                f"{m} {n}" for m, n in sorted(s["transitions"].items())),
        ]
        ps = pump.stats
        lines.append(
            f"priority lane: {ps.get('priority_frames', 0)} frames / "
            f"{ps.get('priority_pkts', 0)} pkts, "
            f"{ps.get('priority_preempts', 0)} window preempts, "
            f"{ps.get('priority_starved', 0)} starved (fault seam)"
        )
        pf = getattr(pump, "priority", None)
        if pf is not None:
            lines.append(
                f"priority rules: {pf.ports.size} ports, "
                f"{pf.prefix_count()} prefixes, {pf.protos.size} "
                f"protos, {pf.flow_count()} marked flows"
            )
        lines.append(
            f"overload shed: {ps.get('drops_overload', 0)} pkts "
            f"(drops_total{{reason=\"overload\"}})"
        )
        return "\n".join(lines)

    def show_tenants(self) -> str:
        """Multi-tenant gateway page (ISSUE 14; vpp_tpu/tenancy/):
        per-tenant config (prefixes, token bucket, capacity slice,
        WFQ weight), live device counters (rx/goodput/rate-limit
        drops/slice failures, bucket fill, slice occupancy) and the
        pump's lane state. Host scalars only — the [T] planes cross
        the transport, never table columns."""
        snap_fn = getattr(self.dp, "tenant_snapshot", None)
        snap = snap_fn() if callable(snap_fn) else None
        if snap is None:
            return "tenancy: off (dataplane.tenancy)"
        lines = ["Multi-tenant gateway (dataplane.tenancy: on)"]
        tio = None
        if self.pump is not None and hasattr(self.pump,
                                             "tenant_io_snapshot"):
            tio = self.pump.tenant_io_snapshot()
        reg = snap["tenants"]
        # tenant 0 always renders: it is the implicit default sink for
        # unmatched traffic, whose counters matter MOST once real
        # tenants are registered
        tids = sorted(set(reg) | {0})
        for tid in tids:
            e = reg.get(tid, {})
            name = e.get("name", f"tenant-{tid}")
            lines.append(f"tenant {tid} ({name}):")
            if e.get("prefixes"):
                lines.append(f"  prefixes     {', '.join(e['prefixes'])}")
            if e.get("vni") is not None:
                lines.append(f"  vni          {e['vni']}")
            rate = int(snap["rate"][tid])
            if rate:
                lines.append(
                    f"  bucket       rate {rate}/tick  burst "
                    f"{int(snap['burst'][tid])}  fill "
                    f"{int(snap['tokens'][tid])}")
            else:
                lines.append("  bucket       unlimited (rate 0)")
            lines.append(
                f"  sessions     {int(snap['occupancy'][tid])} live / "
                f"{int(snap['sess_quota_slots'][tid])} slice slots"
                + ("" if e.get("sess_buckets") else " (unsliced)"))
            lines.append(
                f"  counters     rx {int(snap['rx'][tid])}  goodput "
                f"{int(snap['tx'][tid])}  rl-drops "
                f"{int(snap['rl_drops'][tid])}  slice-fails "
                f"{int(snap['quota_fails'][tid])}")
            if e.get("ml_mode", "inherit") != "inherit" \
                    or e.get("ml_thresh") is not None:
                lines.append(
                    f"  ml           mode {e.get('ml_mode', 'inherit')}"
                    + (f"  thresh {e['ml_thresh']}"
                       if e.get("ml_thresh") is not None else ""))
            if tio is not None:
                io = tio["io"].get(tid)
                q = tio["queued"].get(tid)
                w = tio["weights"].get(tid, 1)
                parts = [f"weight {w}"]
                if io:
                    parts.append(
                        f"frames {io['frames']}  pkts {io['pkts']}  "
                        f"shed {io['shed_pkts']}")
                if q:
                    parts.append(f"queued {q['frames']}f/{q['pkts']}p")
                lines.append("  pump         " + "  ".join(parts))
        if self.pump is not None:
            s = self.pump.stats
            lines.append(
                f"totals: quota-drops "
                f"{s.get('drops_tenant_quota', 0)}  slice-fails "
                f"{s.get('tenant_sess_quota_fails', 0)}  starved "
                f"{s.get('tenant_starved', 0)}")
        return "\n".join(lines)

    def show_io(self) -> str:
        """Pump + IO-daemon counters (the `show interface rx-placement`
        / vector-rates analog for the host IO path)."""
        lines = []
        if self.pump is not None:
            s = self.pump.stats
            lat = self.pump.latency_us()
            mode = getattr(self.pump, "mode", "dispatch")
            lines.append(
                f"pump ({mode}): {s['frames']} frames, {s['pkts']} pkts, "
                f"{s['batches']} batches (max coalesce {s['max_coalesce']}"
                f"), tx-ring-full {s['tx_ring_full']}, "
                f"errors {s['batch_errors']}"
            )
            extra = []
            if s.get("fabric_pkts"):
                extra.append(f"fabric {s['fabric_pkts']} pkts")
            if s.get("icmp_errors"):
                extra.append(f"icmp-errors {s['icmp_errors']}")
            if extra:
                lines.append("pump: " + ", ".join(extra))
            if "inflight_peak" in s:
                lines.append(
                    f"pump overlap: inflight {s.get('inflight', 0)} "
                    f"(peak {s['inflight_peak']}), chained dispatches "
                    f"{s.get('chain_batches', 0)} "
                    f"(max K {s.get('chain_k_peak', 0)})"
                )
            if mode == "persistent":
                # device-resident descriptor rings (ISSUE 7): all
                # HOST-side scalars — occupancy/lag/fill are counted
                # where the windows are staged, so nothing crosses the
                # device transport for this page (the PR 6 rule)
                slots = getattr(self.pump, "ring_slots", 0)
                windows = getattr(self.pump, "ring_windows", 0)
                shipped = int(s.get("ring_windows", 0))
                rframes = int(s.get("ring_frames", 0))
                fill = (100.0 * rframes / (shipped * slots)
                        if shipped and slots else 0.0)
                lines.append(
                    f"pump device-ring: {slots} slots x {windows} "
                    f"windows, {shipped} windows shipped "
                    f"({rframes} frames, fill {fill:.0f}%), "
                    f"in-flight {s.get('ring_inflight', 0)}/{windows}, "
                    f"tx-writeback lag {s.get('ring_lag', 0)}, "
                    f"io-callbacks {s.get('io_callbacks', 0)}"
                )
            drops = {k: int(s.get(k, 0)) for k in
                     ("drops_rx_full", "drops_tx_stall",
                      "drops_shutdown", "drops_error",
                      "drops_overload")}
            if any(drops.values()):
                lines.append(
                    "pump drops by cause (pkts): "
                    f"rx-full {drops['drops_rx_full']}, "
                    f"tx-stall {drops['drops_tx_stall']}, "
                    f"shutdown {drops['drops_shutdown']}, "
                    f"error {drops['drops_error']}, "
                    f"overload {drops['drops_overload']}"
                )
            if "t_pack" in s:
                # stage seconds: fetch_wait is overlapped wait (the
                # ladder hiding the device round trip), fetch the
                # serial result copy
                lines.append(
                    "pump stages (s): "
                    f"pack {s['t_pack']:.3f}, "
                    f"dispatch {s['t_dispatch']:.3f}, "
                    f"fetch-wait {s.get('t_fetch_wait', 0.0):.3f}, "
                    f"fetch {s['t_fetch']:.3f}, "
                    f"write {s.get('t_write', 0.0):.3f}"
                )
            lines.append(
                f"pump batch latency: p50 {lat['p50']:.0f}us "
                f"p99 {lat['p99']:.0f}us over {lat['n']} batches"
            )
        # jit-compile guard (pipeline/dataplane.py): compile-once means
        # each variant shows 1; a RECOMPILED marker is the PR-4
        # regression class live — see /debug/jit for shape signatures
        from vpp_tpu.pipeline.dataplane import (
            device_transfer_totals,
            jit_compile_totals,
            jit_recompiles,
        )
        totals = jit_compile_totals()
        if totals:
            lines.append(
                "jit compiles: "
                + ", ".join(f"{k} {v}" for k, v in sorted(totals.items()))
            )
            recomp = jit_recompiles()
            if recomp:
                lines.append(
                    f"jit RECOMPILED ({len(recomp)} step+shape keys "
                    f"traced >1x — compile-once contract broken)"
                )
        # device-transfer guard (ISSUE 20): bytes fetched per approved
        # site — the serving-path sites must stay rider/descriptor-sized
        xfer = device_transfer_totals()
        if xfer:
            lines.append(
                "device transfer bytes: "
                + ", ".join(f"{k} {v}" for k, v in sorted(xfer.items()))
            )
        if self.io_ctl is not None:
            # the whole block is guarded: the daemon is another process
            # over a socket, so besides being down it may be a different
            # build whose stats dict lacks keys — degrade, never crash
            # the debug CLI
            try:
                d = self.io_ctl.stats()
                ifs = self.io_ctl.list_interfaces()
                lines.append(
                    "io-daemon: rx {rx_frames}f/{rx_pkts}p "
                    "(ring-full {rx_ring_full}, rx-full drops "
                    "{drops_rx_full}p), tx {tx_frames}f/"
                    "{tx_pkts}p, drops {tx_drops}, punts {tx_punts}, "
                    "trunc {trunc_drops}, vxlan {vxlan_encap}e/"
                    "{vxlan_decap}d".format(
                        **{k: d.get(k, "?") for k in (
                            "rx_frames", "rx_pkts", "rx_ring_full",
                            "drops_rx_full",
                            "tx_frames", "tx_pkts", "tx_drops",
                            "tx_punts", "trunc_drops", "vxlan_encap",
                            "vxlan_decap")}
                    )
                )
                lines.append(
                    "io-daemon interfaces: "
                    + (", ".join(f"{i}:{n}" for i, n in sorted(ifs.items()))
                       or "(none)")
                )
            except Exception as e:  # noqa: BLE001 — daemon may be down
                lines.append(f"io-daemon: unreachable ({e})")
        return "\n".join(lines) if lines else "no IO front-end attached"

    def show_neighbors(self) -> str:
        """The IO daemon's (ip → MAC) neighbor table — the `show ip
        arp` analog (static entries from the control plane are marked
        S, rx-learned entries are unmarked)."""
        if self.io_ctl is None:
            return "no IO front-end attached"
        try:
            entries = self.io_ctl.neighbors()
        except Exception as e:  # noqa: BLE001 — daemon may be down
            return f"io-daemon: unreachable ({e})"
        from vpp_tpu.pipeline.vector import ip4_str

        lines = [f"{'ip':<16} {'mac':<18} flags"]
        for ip, mac, pin in sorted(entries):
            mac_s = ":".join(f"{b:02x}" for b in mac)
            lines.append(f"{ip4_str(ip):<16} {mac_s:<18} {'S' if pin else ''}")
        return "\n".join(lines)

    def _live_tracer(self, create: bool = False):
        """The tracer the DATAPLANE records into — arming anything
        else silently captures nothing. Falls back to an explicitly
        injected tracer (in-process test use); ``create`` attaches one
        to the dataplane on demand."""
        t = self.dp.tracer or self.tracer
        if t is None and create:
            from vpp_tpu.trace.tracer import PacketTracer

            t = self.dp.tracer = PacketTracer()
        return t

    def trace_add(self, args: list) -> str:
        """Arm the packet tracer for the next N valid packets (VPP
        `trace add <node> N`): real traffic through the pump takes the
        traced slow path while armed, then reverts to the fused fast
        path."""
        try:
            n = int(args[0]) if args else 16
            if n <= 0:
                raise ValueError("count must be positive")
        except ValueError as e:
            return f"bad argument: {e}"
        tracer = self._live_tracer(create=True)
        if tracer is not self.dp.tracer:
            self.dp.tracer = tracer  # injected tracer: make it live
        tracer.add(n)
        return f"tracing the next {min(n, tracer.max_entries)} packets"

    def trace_clear(self) -> str:
        tracer = self._live_tracer()
        if tracer is None:
            return "no tracer attached"
        tracer.clear()
        return "trace buffer cleared"

    def show_trace(self) -> str:
        tracer = self._live_tracer()
        if tracer is None:
            return "no tracer attached"
        return tracer.format_trace()

    def show_errors(self) -> str:
        if self.stats is None:
            return "no statscollector attached"
        totals = self.stats.totals_snapshot()
        lines = [f"{'counter':<16} {'count':>12}"]
        for k in ("rx", "tx", "drop_ip4", "drop_acl", "drop_no_route", "punt"):
            lines.append(f"{k:<16} {totals[k]:>12}")
        return "\n".join(lines)


def main(argv=None) -> int:
    """Delegates to vpp-tpu-ctl, the vppctl analog: it speaks the
    running agent's CLI socket (cmd/config.py cli_socket)."""
    from vpp_tpu.cmd.ctl import main as ctl_main

    return ctl_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
