"""Declarative config transactions: record, apply, journal, replay.

The reference's NB config path is transactional and *recorded*: the
vpp-agent localclient DSL collects Put/Delete ops into a transaction,
applies it as one unit, and VPP's api-trace keeps a replayable record of
every binary-API message (docker/vpp-vswitch/contiv-vswitch.conf:13-15
`api-trace { on }`; mock/localclient's TxnTracker is the test-side
realization — SURVEY.md §4). Round-2 subsumed the *apply* side with
TableBuilder + epoch swap but had no declarative record/replay
(VERDICT r2 coverage, L2 row).

This module closes that: a ``ConfigTxn`` is a list of declarative ops
(plain data, JSON-serializable) that maps 1:1 onto TableBuilder
mutators. Ops can be

  * **applied** atomically to a Dataplane (stage all ops + one swap
    under the commit lock),
  * **journaled** to an append-only JSONL file (the api-trace analog:
    every applied txn is replayable and auditable),
  * **replayed** from a journal against a fresh builder — config
    recovery / debugging an exact config history on another machine.

Rule lists serialize through ``rule_to_dict``/``rule_from_dict`` so a
journal is self-contained text.
"""

from __future__ import annotations

import ipaddress
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from vpp_tpu.ir.rule import ANY_PORT, Action, ContivRule, Protocol
from vpp_tpu.pipeline.vector import Disposition
from vpp_tpu.trace import spans


# --- rule (de)serialization ---
def rule_to_dict(r: ContivRule) -> Dict[str, Any]:
    return {
        "action": int(r.action),
        "src": str(r.src_network) if r.src_network is not None else None,
        "dst": str(r.dest_network) if r.dest_network is not None else None,
        "proto": int(r.protocol),
        "sport": r.src_port,
        "dport": r.dest_port,
    }


def rule_from_dict(d: Dict[str, Any]) -> ContivRule:
    return ContivRule(
        action=Action(d["action"]),
        src_network=(ipaddress.ip_network(d["src"])
                     if d.get("src") else None),
        dest_network=(ipaddress.ip_network(d["dst"])
                      if d.get("dst") else None),
        protocol=Protocol(d["proto"]),
        src_port=d.get("sport", ANY_PORT),
        dest_port=d.get("dport", ANY_PORT),
    )


# op name -> TableBuilder method; the txn layer is a thin declarative
# skin over the builder, so the set of legal ops IS the builder API
_OPS = (
    "set_interface", "set_if_local_table", "add_route", "del_route",
    "set_nh_group", "del_nh_group",
    "set_local_table", "clear_local_table", "set_global_table",
    "set_nat_mapping", "clear_nat", "set_snat_ip",
    "set_ml_model", "clear_ml_model",
    "set_tenant", "clear_tenants", "set_tenant_ml",
    "set_service", "del_service", "clear_services", "set_vtep_ip",
)
_RULE_OPS = {"set_local_table", "set_global_table"}


@dataclass
class ConfigTxn:
    """One declarative transaction: ordered ops + optional label."""

    label: str = ""
    ops: List[Dict[str, Any]] = field(default_factory=list)

    def _record(self, op: str, **kw: Any) -> "ConfigTxn":
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r}")
        self.ops.append({"op": op, **kw})
        return self

    # --- the DSL (mirrors TableBuilder's mutators) ---
    def set_interface(self, if_index: int, if_type: int,
                      local_table: int = -1,
                      apply_global: bool = False) -> "ConfigTxn":
        return self._record("set_interface", if_index=if_index,
                            if_type=int(if_type),
                            local_table=local_table,
                            apply_global=bool(apply_global))

    def set_if_local_table(self, if_index: int, slot: int) -> "ConfigTxn":
        return self._record("set_if_local_table", if_index=if_index,
                            slot=slot)

    def add_route(self, prefix: str, tx_if: int, disposition: int,
                  next_hop: int = 0, node_id: int = -1,
                  snat: bool = False,
                  slot: Optional[int] = None,
                  group: Optional[int] = None) -> "ConfigTxn":
        """``slot`` pins the FIB slot (recorded from the builder's
        resolved placement, so replay reproduces byte-identical
        tables); None lets replay allocate. ``group`` names an ECMP
        next-hop group (ISSUE 15)."""
        kw = dict(prefix=prefix, tx_if=tx_if,
                  disposition=int(disposition), next_hop=next_hop,
                  node_id=node_id, snat=bool(snat))
        if slot is not None:
            kw["slot"] = int(slot)
        if group is not None:
            kw["group"] = int(group)
        return self._record("add_route", **kw)

    def del_route(self, prefix: str) -> "ConfigTxn":
        return self._record("del_route", prefix=prefix)

    # --- ECMP next-hop groups (ISSUE 15) ---
    def set_nh_group(self, gid: int, members) -> "ConfigTxn":
        """``members`` is the distinct member list as
        TableBuilder.set_nh_group normalizes it — plain JSON rows
        ``[next_hop, tx_if, node_id]``. Replay reruns the sticky way
        fill deterministically (the same registry always compiles the
        same assignment)."""
        return self._record("set_nh_group", gid=int(gid),
                            members=[list(m) for m in members])

    def del_nh_group(self, gid: int) -> "ConfigTxn":
        return self._record("del_nh_group", gid=int(gid))

    def set_local_table(self, slot: int,
                        rules: Sequence[ContivRule]) -> "ConfigTxn":
        return self._record("set_local_table", slot=slot,
                            rules=[rule_to_dict(r) for r in rules])

    def clear_local_table(self, slot: int) -> "ConfigTxn":
        return self._record("clear_local_table", slot=slot)

    def set_global_table(self, rules: Sequence[ContivRule]) -> "ConfigTxn":
        return self._record("set_global_table",
                            rules=[rule_to_dict(r) for r in rules])

    def set_nat_mapping(self, slot: int, ext_ip: int, ext_port: int,
                        proto: int, backends: Sequence[tuple],
                        boff: int, self_snat: bool = False) -> "ConfigTxn":
        return self._record("set_nat_mapping", slot=slot, ext_ip=ext_ip,
                            ext_port=ext_port, proto=proto,
                            backends=[list(b) for b in backends],
                            boff=boff, self_snat=bool(self_snat))

    def clear_nat(self) -> "ConfigTxn":
        return self._record("clear_nat")

    def set_snat_ip(self, ip: int) -> "ConfigTxn":
        return self._record("set_snat_ip", ip=ip)

    # --- VXLAN overlay + service LB (ISSUE 19) ---
    def set_vtep_ip(self, ip: int) -> "ConfigTxn":
        return self._record("set_vtep_ip", ip=ip)

    def set_service(self, vip_ip: int, port: int, proto: int,
                    backends: Sequence[tuple],
                    self_snat: bool = False) -> "ConfigTxn":
        """``backends`` is the distinct backend list as
        TableBuilder.set_service normalizes it — plain JSON rows
        ``[ip, port, weight]``. Replay reruns the sticky way fill
        deterministically (the set_nh_group journaling rationale)."""
        return self._record("set_service", vip_ip=int(vip_ip),
                            port=int(port), proto=int(proto),
                            backends=[list(b) for b in backends],
                            self_snat=bool(self_snat))

    def del_service(self, vip_ip: int, port: int,
                    proto: int) -> "ConfigTxn":
        return self._record("del_service", vip_ip=int(vip_ip),
                            port=int(port), proto=int(proto))

    def clear_services(self) -> "ConfigTxn":
        return self._record("clear_services")

    def set_ml_model(self, model) -> "ConfigTxn":
        """``model`` is an MlModel or its JSON dict form; the journal
        stores the dict (tiny — a few hundred int8 weights), so replay
        reproduces the exact staged blob."""
        if hasattr(model, "to_dict"):
            model = model.to_dict()
        return self._record("set_ml_model", model=model)

    def clear_ml_model(self) -> "ConfigTxn":
        return self._record("clear_ml_model")

    # --- multi-tenant gateway mode (ISSUE 14) ---
    def set_tenant(self, tid: int, **kw: Any) -> "ConfigTxn":
        """``kw`` is the tenant entry as TableBuilder.set_tenant takes
        it (prefixes/vni/rate/burst/slices/weight/ml_*) — plain JSON
        data, so the journal replays the exact staged tenant."""
        return self._record("set_tenant", tid=int(tid), **kw)

    def clear_tenants(self) -> "ConfigTxn":
        return self._record("clear_tenants")

    def set_tenant_ml(self, tid: int, ml_mode: str = "inherit",
                      ml_thresh: Optional[int] = None) -> "ConfigTxn":
        return self._record("set_tenant_ml", tid=int(tid),
                            ml_mode=ml_mode, ml_thresh=ml_thresh)

    # --- apply / serialize ---
    def apply_to_builder(self, builder) -> None:
        """Stage every op on a TableBuilder (no swap — the caller owns
        the commit boundary)."""
        for entry in self.ops:
            op = entry["op"]
            kw = {k: v for k, v in entry.items() if k != "op"}
            if op in _RULE_OPS:
                kw["rules"] = [rule_from_dict(d) for d in kw["rules"]]
            if op in ("set_nat_mapping", "set_service"):
                kw["backends"] = [tuple(b) for b in kw["backends"]]
            if op == "add_route":
                kw["disposition"] = Disposition(kw["disposition"])
            getattr(builder, op)(**kw)

    def to_dict(self) -> Dict[str, Any]:
        return {"label": self.label, "ops": self.ops}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ConfigTxn":
        return cls(label=d.get("label", ""), ops=list(d.get("ops", [])))


class TxnJournal:
    """Append-only JSONL record of applied transactions (api-trace
    analog). Thread-safe; replayable."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self._lock = threading.Lock()
        self.applied = 0
        # torn trailing lines tolerated by the last load() (crash
        # mid-append); surfaced by `show config-history`
        self.torn_lines = 0

    def record(self, txn: ConfigTxn, epoch: int) -> None:
        entry = {"t": time.time(), "epoch": epoch, **txn.to_dict()}
        with self._lock:
            self.applied += 1
            if not self.path:
                return
            with open(self.path, "a") as f:
                f.write(json.dumps(entry, separators=(",", ":")) + "\n")
                # fsync: the journal IS the config-recovery record; a
                # crash right after apply_txn must not lose the txn the
                # live dataplane already enforced (same discipline as
                # the kvstore snapshots)
                f.flush()
                os.fsync(f.fileno())

    def load_entries(self) -> List[Dict[str, Any]]:
        """Raw journal entries (t/epoch/label/ops dicts) in file order.

        A torn TRAILING line — the crash-mid-append case: record()
        appends then fsyncs, so a kill between write() and the page
        hitting disk can leave a truncated last line — is tolerated and
        counted in ``torn_lines`` instead of raising. A malformed line
        with valid entries AFTER it is real corruption and still
        raises: silently skipping it would replay a history the live
        dataplane never enforced."""
        self.torn_lines = 0
        if not self.path or not os.path.exists(self.path):
            return []
        with open(self.path) as f:
            lines = [(i, ln.strip()) for i, ln in enumerate(f, 1)]
        lines = [(i, ln) for i, ln in lines if ln]
        out: List[Dict[str, Any]] = []
        for pos, (lineno, line) in enumerate(lines):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if pos == len(lines) - 1:
                    self.torn_lines = 1
                    break
                raise json.JSONDecodeError(
                    f"corrupt journal line {lineno} (not the trailing "
                    f"line — refusing to skip mid-history)", line, 0)
        return out

    def load(self) -> List[ConfigTxn]:
        return [ConfigTxn.from_dict(d) for d in self.load_entries()]

    def load_tail_entries(self, limit: int,
                          max_bytes: int = 1 << 20) -> List[Dict[str, Any]]:
        """The last ``limit`` raw entries, reading at most ``max_bytes``
        from the file END — the /debug/txns serving path must stay
        O(limit) however large a long-lived agent's journal grows.
        Torn-trailing-line tolerance matches load_entries(); a line cut
        at the seek boundary is discarded (it has complete entries
        after it, so it is a window artifact, not corruption)."""
        self.torn_lines = 0
        if not self.path or not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            start = max(0, size - max_bytes)
            f.seek(start)
            data = f.read().decode(errors="replace")
        lines = data.splitlines()
        if start > 0 and lines:
            lines = lines[1:]  # first line may start mid-entry
        lines = [ln.strip() for ln in lines if ln.strip()]
        out: List[Dict[str, Any]] = []
        for pos, line in enumerate(lines):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                if pos == len(lines) - 1:
                    self.torn_lines = 1
                    break
                raise json.JSONDecodeError(
                    "corrupt journal line in tail window (not the "
                    "trailing line — refusing to skip mid-history)",
                    line, 0)
        return out[-limit:]

    def replay(self, builder) -> int:
        """Re-stage every journaled txn in order onto ``builder``;
        returns the txn count. The caller swaps once at the end —
        replay is a bulk restore, not a re-enactment of every epoch."""
        txns = self.load()
        for txn in txns:
            txn.apply_to_builder(builder)
        return len(txns)


def apply_txn(dataplane, txn: ConfigTxn,
              journal: Optional[TxnJournal] = None) -> int:
    """Apply one declarative transaction atomically: stage all ops and
    publish ONE new epoch under the commit lock (the localclient
    Send().ReceiveReply() analog). Returns the new epoch.

    All-or-nothing: a failing op (FIB full, slot out of range, …) rolls
    the builder back to its pre-txn snapshot, so the next unrelated
    commit can never publish a half-applied transaction. Journaling
    happens INSIDE the commit lock — entries land in epoch order, so a
    replay reconstructs exactly the history the live dataplane enforced.

    The whole stage+swap commit runs under a "txn" span, so an applied
    txn's timeline attributes staging separately from the epoch swap
    (the swap opens its own child span and feeds the
    ``vpp_tpu_txn_commit_seconds`` histogram)."""
    with spans.RECORDER.span(
        "txn", f"apply-txn {txn.label or '(unlabelled)'}",
        ops=len(txn.ops),
    ):
        with dataplane.commit_lock:
            snap = dataplane.builder.state_snapshot()
            try:
                txn.apply_to_builder(dataplane.builder)
            except Exception:
                dataplane.builder.state_restore(snap)
                raise
            epoch = dataplane.swap()
            # a dataplane with its own journal + recording already
            # recorded this txn during swap(); only record here when the
            # caller's journal is a different one (or the dataplane has
            # none)
            if journal is not None and journal is not dataplane.journal:
                journal.record(txn, epoch)
    return epoch
