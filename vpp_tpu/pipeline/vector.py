"""Packet vectors: fixed-size struct-of-arrays batches of packet headers.

VPP processes packets in frames of up to 256; the same frame model maps
directly onto TPU vector lanes (256 = 2×128 lanes), so VEC=256 is the
native batch unit here too. Header fields are SoA int32/uint32 arrays —
TPU's natural integer width — rather than VPP's array-of-structs vlib
buffers. Payload bytes (needed only for encap/decap and host IO) travel
in a separate byte buffer and never enter the classify/NAT/FIB kernels.

Reference analog: vlib frames + vnet buffer metadata (external VPP C).
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

# Native packet-frame size (packets per vector).
VEC = 256


class Disposition(enum.IntEnum):
    """Where a packet goes after the pipeline — VPP's "next node" analog."""

    DROP = 0        # error-drop / policy deny
    LOCAL = 1       # tx to a local pod/host interface
    REMOTE = 2      # tx toward another node (ICI/DCN or VXLAN uplink)
    HOST = 3        # punt to the host stack
    UNKNOWN = 4     # not yet determined (pipeline-internal)


class PacketVector(NamedTuple):
    """A frame of packet headers in SoA layout. All arrays have shape [VEC]
    (or [B, VEC] when batched); dtypes are fixed as noted.

    ``flags`` bit 0 = packet slot valid (frames may be partially filled).
    """

    src_ip: jnp.ndarray   # uint32, IPv4 address (network-byte-order value)
    dst_ip: jnp.ndarray   # uint32
    proto: jnp.ndarray    # int32, IANA protocol number (6 TCP, 17 UDP, 1 ICMP)
    sport: jnp.ndarray    # int32, L4 source port (0 for portless protos)
    dport: jnp.ndarray    # int32
    ttl: jnp.ndarray      # int32
    pkt_len: jnp.ndarray  # int32, total IP length in bytes
    rx_if: jnp.ndarray    # int32, software interface index the packet arrived on
    flags: jnp.ndarray    # int32 bitfield; bit0 = valid

    @property
    def valid(self) -> jnp.ndarray:
        return (self.flags & 1) == 1


FLAG_VALID = 1


def ip4(addr: str) -> int:
    """Dotted-quad string → uint32 host-order integer value."""
    a, b, c, d = (int(x) for x in addr.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


def ip4_str(value: int) -> str:
    value = int(value) & 0xFFFFFFFF
    return f"{value >> 24}.{(value >> 16) & 255}.{(value >> 8) & 255}.{value & 255}"


def make_packet_vector(
    packets: Optional[list] = None,
    n: int = VEC,
    np_mod=np,
) -> PacketVector:
    """Build a PacketVector from a list of dicts (host-side test/ingest path).

    Each dict may carry: src, dst (dotted strings or ints), proto, sport,
    dport, ttl, len, rx_if. Missing slots are zero-filled and marked invalid.
    """
    packets = packets or []
    assert len(packets) <= n, f"{len(packets)} packets > frame size {n}"

    def col(name, default, dtype=np.int32):
        out = np.full((n,), default, dtype=dtype)
        for i, p in enumerate(packets):
            v = p.get(name, default)
            if name in ("src", "dst") and isinstance(v, str):
                v = ip4(v)
            out[i] = v
        return out

    flags = np.zeros((n,), dtype=np.int32)
    flags[: len(packets)] = FLAG_VALID
    return PacketVector(
        src_ip=jnp.asarray(col("src", 0, np.uint32)),
        dst_ip=jnp.asarray(col("dst", 0, np.uint32)),
        proto=jnp.asarray(col("proto", 6)),
        sport=jnp.asarray(col("sport", 0)),
        dport=jnp.asarray(col("dport", 0)),
        ttl=jnp.asarray(col("ttl", 64)),
        pkt_len=jnp.asarray(col("len", 64)),
        rx_if=jnp.asarray(col("rx_if", 0)),
        flags=jnp.asarray(flags),
    )
