"""Crash-consistent incremental session snapshot/restore (ISSUE 8).

A production gateway holding 10M+ resident sessions (docs/SESSIONS.md)
must survive an agent restart without dropping them: the fastpath hit
rate — and with it the headline throughput — collapses to zero while
every flow re-establishes. ROADMAP item 2 left "incremental
snapshot/restore of the 10M-slot table" open; this module closes it.

Design, shaped by the same constraints as the sweep/ring work:

* **The ~1.1 GB table never ships in one transfer and never stalls the
  fused step.** The table is split into fixed BUCKET-RANGE chunks
  (``chunk_buckets`` buckets of all session columns — config-static
  shape, like ``sess_ways``). Each drained chunk is ONE bounded
  device→host fetch of a few MB, paced (``pace_s``) off the hot path
  in the agent's maintenance thread. The step never blocks: the
  snapshotter grabs ONE immutable tables reference under the dataplane
  lock and drains from that epoch while traffic keeps publishing new
  ones — the functional-pytree analog of a consistent point-in-time
  snapshot, for free.
* **Incremental via content digests, not a hot-path dirty bitmap.**
  Each snapshot computes a per-chunk content digest ON DEVICE (one
  O(table) elementwise pass + a [n_chunks] reduction — no transfer
  beyond n_chunks words) and drains only chunks whose digest moved
  since the last published manifest. An insert-time dirty-scatter was
  considered (piggybacked on the ``session_sweep`` walk) and rejected:
  it taxes every insert to speed up a maintenance-cadence operation,
  and clearing dirty bits races concurrent steps — content digests
  are computed against the immutable snapshot reference, so they
  cannot miss or double-report a write. The digest is a 32-bit mix
  (position-weighted sum of per-slot column folds): collision odds
  per chunk per snapshot are ~2^-32 — a stale-chunk *non-ship* needs
  a colliding digest in the SAME chunk slot, which is noise next to
  the torn-write windows this module actually closes.
* **Crash consistency by construction.** Chunk files are written and
  fsync'd FIRST; the manifest (which alone gives chunks meaning) is
  published LAST via write-tmp → fsync → atomic ``os.replace``. A
  crash at any point leaves the previous manifest generation fully
  intact: a trailing torn chunk is an unreferenced file, GC'd by the
  next successful snapshot (the torn-journal discipline of
  pipeline/txn.py, applied to bulk state). Every chunk carries a CRC32
  — a referenced chunk that fails its CRC at restore (bit rot, truncation
  under the manifest's feet) refuses the WHOLE restore cleanly: the
  dataplane cold-starts instead of serving a half-restored table.
* **Restore rides the epoch-swap path.** ``restore_into`` loads the
  manifest generation, rebases timestamps to the new process's clock
  (``time' = time - snap_now``: ages are preserved, so an entry with
  200 s of idle age at snapshot still expires 100 s after a restart
  with a 300 s timeout) and publishes through
  ``TableBuilder.to_device(sessions=...)`` — the same SESSION_FIELDS
  contract an epoch swap's carry-over uses.

Fault points (vpp_tpu/testing/faults.py): ``snapshot.chunk`` fires
inside a chunk write and leaves a torn file; ``snapshot.manifest``
fires before the atomic rename — both simulate a crash mid-snapshot
for the chaos schedules in tests/test_chaos.py.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import struct
import threading
import time
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from vpp_tpu.pipeline.dataplane import count_device_transfer
from vpp_tpu.pipeline.tables import (
    SESSION_FIELDS,
    _SESSION_SHAPE,
    natsess_slots_of,
    session_shapes,
)
from vpp_tpu.testing import faults

log = logging.getLogger("vpp_tpu.snapshot")

MANIFEST = "manifest.json"
FORMAT_VERSION = 1
_MAGIC = b"VPPSNAP1"
_HDR = struct.Struct("<8sII")  # magic, crc32(payload), payload length

# per-table column lists, in SESSION_FIELDS order (the single source of
# chunk payload layout — restore relies on the same iteration order)
TABLE_COLS: Dict[str, Tuple[str, ...]] = {
    "sess": tuple(k for k in SESSION_FIELDS
                  if _SESSION_SHAPE[k] == "sess"),
    "natsess": tuple(k for k in SESSION_FIELDS
                     if _SESSION_SHAPE[k] == "natsess"),
}
SCALAR_FIELDS: Tuple[str, ...] = tuple(
    k for k in SESSION_FIELDS if _SESSION_SHAPE[k] == "scalar")

# restore outcome reasons (the label axis of
# vpp_tpu_snapshot_restore_total; stats/collector.py exports all of
# them so an absent outcome is a visible 0, not a missing series)
RESTORE_OUTCOMES = (
    "restored", "no_manifest", "bad_manifest", "version", "geometry",
    "missing_chunk", "crc_mismatch", "error",
)


@functools.lru_cache(maxsize=8)
def _fetch_fn(chunk_buckets: int):
    """Jitted bounded chunk drain for one bucket-range: stacks every
    column's ``[chunk_buckets, W]`` slice into ONE ``[C, CB, W]`` int32
    block, so a chunk costs exactly one device→host fetch. ``start``
    is a traced scalar — draining the whole ring never retraces."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def fetch(cols, start):
        rows = [
            lax.dynamic_slice(
                c, (start, jnp.int32(0)), (chunk_buckets, c.shape[1]))
            for c in cols
        ]
        return jnp.stack(
            [lax.bitcast_convert_type(r, jnp.int32) for r in rows])

    return jax.jit(fetch)


@functools.lru_cache(maxsize=8)
def _digest_fn(chunk_buckets: int):
    """Jitted per-chunk content digest: fold all columns elementwise
    (multiplicative mix), finalize per slot, then position-weight and
    sum within each chunk so reorderings inside a chunk change the
    digest. Returns ``[n_chunks]`` uint32 — the only bytes that cross
    the transport when nothing changed."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def digest(cols):
        acc = None
        for c in cols:
            u = lax.bitcast_convert_type(c, jnp.uint32)
            u = u.reshape(u.shape[0] // chunk_buckets, -1)
            acc = u if acc is None else acc * jnp.uint32(0x9E3779B1) + u
        e = acc ^ (acc >> 15)
        e = e * jnp.uint32(0x2545F491)
        e = e ^ (e >> 13)
        m = e.shape[1]
        pos = (jnp.arange(m, dtype=jnp.uint32) << 1) | jnp.uint32(1)
        return jnp.sum(e * pos[None, :], axis=1, dtype=jnp.uint32)

    return jax.jit(digest)


def _chunk_name(table: str, idx: int, gen: int,
                node: Optional[int] = None) -> str:
    if node is None:
        return f"{table}-{idx:05d}-g{gen}.chunk"
    return f"{table}-n{node:03d}-{idx:05d}-g{gen}.chunk"


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: file-content fsyncs alone don't make the
    directory entries durable, and a power loss could otherwise leave
    a published manifest pointing at chunk files whose dir entries
    never landed (while GC already unlinked the previous generation's)
    — exactly the no-restorable-generation hole the chunks-first/
    manifest-last ordering exists to close. Best effort: some
    filesystems refuse O_RDONLY-fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _geometry_of(config) -> Dict[str, int]:
    ways = int(getattr(config, "sess_ways", 4))
    return {
        "sess_slots": int(config.sess_slots),
        "sess_ways": ways,
        "natsess_slots": int(natsess_slots_of(config)),
    }


def _mesh_of(dp) -> Optional[Dict[str, int]]:
    """The mesh geometry of a CLUSTER staging handle (None for the
    standalone Dataplane). Recorded in the manifest and REFUSED on
    mismatch at restore: a snapshot's per-shard bucket ranges only mean
    something on the mesh that drained them — restoring a 4-shard
    table onto a 2-shard mesh would interleave bucket ownership wrong,
    and misdelivering NAT replies is worse than a cold start."""
    mesh = getattr(dp, "mesh", None)
    if mesh is None:
        return None
    from vpp_tpu.parallel.partition import NODE_AXIS, RULE_AXIS

    return {
        "n_nodes": int(mesh.shape[NODE_AXIS]),
        "rule_shards": int(mesh.shape[RULE_AXIS]),
    }


class SessionSnapshotter:
    """Owns one snapshot directory for one dataplane.

    Thread model: ``snapshot()``/``maybe_snapshot()`` run on ONE caller
    (the agent maintenance thread); a concurrent call returns None
    instead of stacking drains. ``stats_snapshot()`` and the degraded
    flag are safe from any thread (CLI/collector). The long drain works
    entirely on locals; ``self`` state flips under ``_lock`` only at
    the edges.
    """

    def __init__(self, dataplane, directory: str,
                 chunk_buckets: int = 4096, pace_s: float = 0.0):
        self.dp = dataplane
        self.directory = directory
        if chunk_buckets <= 0 or (chunk_buckets & (chunk_buckets - 1)):
            raise ValueError(
                f"snapshot_chunk_buckets must be a power of two, got "
                f"{chunk_buckets}")
        self.chunk_buckets = int(chunk_buckets)
        self.pace_s = float(pace_s)
        self._lock = threading.Lock()
        self._snapping = False
        # last successfully PUBLISHED manifest (dict) — the diff base
        # for incremental drains; loaded from disk at ctor so the first
        # snapshot after a process restart is already incremental
        self._manifest: Optional[dict] = None
        self.stats = {
            "generation": 0,
            "snapshots": 0,
            "snapshot_failures": 0,
            "consecutive_failures": 0,
            "chunks_written": 0,
            "chunks_skipped": 0,
            "bytes_written": 0,
            "chunk_seconds": 0.0,
            "last_snapshot_wall": 0.0,
            "last_error": "",
            "restore_outcome": "",
            "restores": {k: 0 for k in RESTORE_OUTCOMES},
        }
        os.makedirs(directory, exist_ok=True)
        m = self._load_manifest()
        if isinstance(m, dict):  # "bad" sentinel = present-but-torn:
            # the next snapshot starts a fresh generation history
            with self._lock:
                self._manifest = m
                self.stats["generation"] = int(m.get("generation", 0))
                self.stats["last_snapshot_wall"] = float(
                    m.get("t_wall", 0.0))

    # --- observability ---
    @property
    def degraded(self) -> bool:
        """True while the most recent snapshot attempt failed — the
        ``vpp_tpu_degraded{component="snapshot"}`` signal."""
        with self._lock:
            return self.stats["consecutive_failures"] > 0

    def due(self, interval_s: float) -> bool:
        """Whether maybe_snapshot(interval_s) would drain now — lets
        the agent pay pre-drain work (the persistent pump's session
        sync, a full device copy) only when a snapshot is actually
        coming, not on every maintenance tick."""
        with self._lock:
            last = self.stats["last_snapshot_wall"]
        return not last or time.time() - last >= interval_s

    def stats_snapshot(self) -> dict:
        with self._lock:
            s = dict(self.stats)
            s["restores"] = dict(self.stats["restores"])
        s["age_s"] = (time.time() - s["last_snapshot_wall"]
                      if s["last_snapshot_wall"] else -1.0)
        return s

    # --- snapshot (writer side) ---
    def maybe_snapshot(self, interval_s: float) -> Optional[int]:
        """Interval-paced snapshot for the maintenance tick: drains
        only when the last published generation is older than
        ``interval_s``. Returns the new generation or None."""
        if not self.due(interval_s):
            return None
        return self.snapshot()

    def final_snapshot(self, timeout: float = 120.0) -> Optional[int]:
        """The parting snapshot for a clean shutdown: unlike
        ``snapshot()`` it WAITS OUT an in-flight maintenance drain
        (which started from pre-merge state) and then drains once
        more, so the generation on disk includes everything the pump
        merged back at stop. Returns the generation, or None on a
        real failure (already counted) or timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            gen = self.snapshot()
            if gen is not None:
                return gen
            with self._lock:
                in_flight = self._snapping
            if not in_flight:
                return None  # our own attempt ran and failed
            time.sleep(0.1)
        return None

    def snapshot(self) -> Optional[int]:
        """Drain dirty chunks and publish a new manifest generation.
        Returns the generation, or None when a snapshot is already in
        flight. Failures (including injected ones) mark the
        snapshotter degraded and re-raise nothing — a broken disk must
        not take the maintenance loop (and with it liveness
        keepalives) down; the error is exported instead."""
        with self._lock:
            if self._snapping:
                return None
            self._snapping = True
            prev = self._manifest
            gen = self.stats["generation"] + 1
        try:
            manifest = self._drain(gen, prev)
            with self._lock:
                self._manifest = manifest
                self.stats["generation"] = gen
                self.stats["snapshots"] += 1
                self.stats["consecutive_failures"] = 0
                self.stats["last_error"] = ""
                self.stats["last_snapshot_wall"] = manifest["t_wall"]
            self._gc(manifest)
            return gen
        except Exception as e:  # noqa: BLE001 — degraded, not fatal
            log.exception("session snapshot failed (generation %d)", gen)
            with self._lock:
                self.stats["snapshot_failures"] += 1
                self.stats["consecutive_failures"] += 1
                self.stats["last_error"] = f"{type(e).__name__}: {e}"
            return None
        finally:
            with self._lock:
                self._snapping = False

    def _drain(self, gen: int, prev: Optional[dict]) -> dict:
        import jax
        import jax.numpy as jnp

        dp = self.dp
        # ONE immutable epoch reference: every chunk of this manifest
        # generation comes from the same tables pytree, so the
        # snapshot is point-in-time consistent by construction even
        # while traffic keeps publishing newer epochs
        with dp._lock:
            tables = dp.tables
            if tables is None:
                raise RuntimeError(
                    "staging handle has no live tables to snapshot")
            now = max(dp._now, dp.clock_ticks())
        geometry = _geometry_of(dp.config)
        mesh = _mesh_of(dp)
        prev_ok = (prev is not None
                   and prev.get("version") == FORMAT_VERSION
                   and prev.get("config") == geometry
                   and prev.get("mesh") == mesh
                   and prev.get("chunk_buckets") == self.chunk_buckets)
        manifest = {
            "version": FORMAT_VERSION,
            "generation": gen,
            "now": int(now),
            "t_wall": time.time(),
            "config": geometry,
            "mesh": mesh,
            "chunk_buckets": self.chunk_buckets,
            "scalars": {},
            "tables": {},
        }
        for f in SCALAR_FIELDS:
            v = np.asarray(getattr(tables, f))
            # cluster handles stack the cursor scalars per node ([N])
            manifest["scalars"][f] = (
                [int(x) for x in v] if v.ndim else int(v))
        written = skipped = wbytes = 0
        t_chunks = 0.0
        # node rows to drain: the standalone table is "one node" with
        # no leading axis; the cluster table drains per (node, shard)
        # bucket range — chunks are capped to the per-shard range so a
        # chunk file never straddles a shard boundary and the manifest
        # records which shard's range each chunk covers
        nodes = (None,) if mesh is None else tuple(
            range(mesh["n_nodes"]))
        shards = 1 if mesh is None else mesh["rule_shards"]
        for table, fields in TABLE_COLS.items():
            all_cols = tuple(getattr(tables, f) for f in fields)
            n_buckets = int(all_cols[0].shape[-2])
            per_shard = n_buckets // shards
            cb = min(self.chunk_buckets, per_shard)
            n_chunks = n_buckets // cb
            valid = tables.sess_valid if table == "sess" \
                else tables.natsess_valid
            flagged = int(np.asarray(jnp.sum(valid)))
            prev_tab = (prev["tables"][table]
                        if prev_ok and table in prev.get("tables", {})
                        else None)
            prev_chunks = (prev_tab["chunks"] if prev_tab is not None
                           and prev_tab.get("chunk_buckets") == cb
                           else None)
            fetch = _fetch_fn(cb)
            entries = []
            for node in nodes:
                cols = (all_cols if node is None
                        else tuple(c[node] for c in all_cols))
                digests = np.asarray(_digest_fn(cb)(cols))
                for idx in range(n_chunks):
                    flat = (0 if node is None else node) * n_chunks + idx
                    d = int(digests[idx])
                    if prev_chunks is not None and \
                            flat < len(prev_chunks) and \
                            prev_chunks[flat]["digest"] == d:
                        # content unchanged since the published
                        # generation: the old file keeps serving it
                        entries.append(dict(prev_chunks[flat]))
                        skipped += 1
                        continue
                    t0 = time.perf_counter()
                    block = np.asarray(
                        jax.device_get(fetch(cols, np.int32(idx * cb))))
                    count_device_transfer("snapshot.drain", block)
                    payload = block.tobytes()
                    name = _chunk_name(table, idx, gen, node)
                    crc = self._write_chunk(
                        os.path.join(self.directory, name), payload)
                    t_chunks += time.perf_counter() - t0
                    entry = {"file": name, "digest": d, "crc": crc,
                             "start": idx * cb,
                             "shard": (idx * cb) // per_shard}
                    if node is not None:
                        entry["node"] = node
                    entries.append(entry)
                    written += 1
                    wbytes += len(payload)
                    if self.pace_s:
                        time.sleep(self.pace_s)
            manifest["tables"][table] = {
                "chunk_buckets": cb,
                "n_chunks": n_chunks,
                "flagged": flagged,
                "chunks": entries,
            }
        self._publish_manifest(manifest)
        with self._lock:
            self.stats["chunks_written"] += written
            self.stats["chunks_skipped"] += skipped
            self.stats["bytes_written"] += wbytes
            self.stats["chunk_seconds"] += t_chunks
        return manifest

    @staticmethod
    def _write_chunk(path: str, payload: bytes) -> int:
        """One chunk file: header (magic, crc32, length) + payload,
        fsync'd. The ``snapshot.chunk`` fault fires mid-write and
        leaves a TORN file behind — exactly what a crash between the
        header and the tail produces — before aborting the snapshot;
        the file is unreferenced (no manifest points at it yet), so
        restore keeps working from the previous generation."""
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        with open(path, "wb") as f:
            f.write(_HDR.pack(_MAGIC, crc, len(payload)))
            try:
                faults.fire("snapshot.chunk")
            except BaseException:
                f.write(payload[: len(payload) // 2])
                f.flush()
                raise
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        return crc

    def _publish_manifest(self, manifest: dict) -> None:
        """tmp → fsync → atomic rename: the manifest flip IS the
        commit point. The ``snapshot.manifest`` fault fires before the
        rename (crash with every chunk durable but the generation
        unpublished — the previous generation stays the truth)."""
        path = os.path.join(self.directory, MANIFEST)
        # every chunk's CONTENT is fsync'd; make their directory
        # entries durable BEFORE the manifest can reference them
        _fsync_dir(self.directory)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        faults.fire("snapshot.manifest")
        os.replace(tmp, path)
        # ...and the rename itself (the commit point) likewise
        _fsync_dir(self.directory)

    def _gc(self, manifest: dict) -> None:
        """Delete chunk files the just-published manifest no longer
        references (superseded generations, torn leftovers). Best
        effort — an undeletable file costs disk, never correctness."""
        live = {e["file"] for t in manifest["tables"].values()
                for e in t["chunks"]}
        try:
            for name in os.listdir(self.directory):
                if name.endswith(".chunk") and name not in live:
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:
                        pass
                elif name.endswith(".tmp"):
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:
                        pass
        except OSError:
            pass

    # --- restore (reader side) ---
    def _load_manifest(self) -> Optional[dict]:
        path = os.path.join(self.directory, MANIFEST)
        try:
            with open(path) as f:
                m = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, json.JSONDecodeError, ValueError):
            return "bad"  # sentinel: present but unreadable
        return m if isinstance(m, dict) else "bad"

    def _count_restore(self, outcome: str, detail: str = "") -> None:
        with self._lock:
            self.stats["restore_outcome"] = outcome
            self.stats["restores"][outcome] = \
                self.stats["restores"].get(outcome, 0) + 1
            if detail:
                self.stats["last_error"] = detail
        if outcome != "restored":
            log.warning("session restore: %s%s", outcome,
                        f" ({detail})" if detail else "")

    def restore(self) -> Tuple[Optional[Dict[str, np.ndarray]], str]:
        """Load the last published generation into host session
        arrays. Returns ``(sessions, outcome)`` — sessions is None on
        any refusal, and a refusal is always CLEAN: either the whole
        generation loads and verifies, or the caller cold-starts. A
        half-restored table (some chunks new, some stale or zero) is
        the one state this path must never produce — misdelivering
        NAT replies is worse than re-establishing flows."""
        m = self._load_manifest()
        if m is None:
            self._count_restore("no_manifest")
            return None, "no_manifest"
        if m == "bad":
            self._count_restore("bad_manifest")
            return None, "bad_manifest"
        if m.get("version") != FORMAT_VERSION:
            self._count_restore("version",
                                f"manifest version {m.get('version')!r}")
            return None, "version"
        geometry = _geometry_of(self.dp.config)
        if m.get("config") != geometry:
            self._count_restore(
                "geometry",
                f"snapshot {m.get('config')} != configured {geometry}")
            return None, "geometry"
        mesh = _mesh_of(self.dp)
        if m.get("mesh") != mesh:
            # a per-shard drain only restores onto the SAME mesh shape
            # (node count and rule-shard count): refuse cleanly —
            # the fleet cold-starts instead of interleaving bucket
            # ownership wrong
            self._count_restore(
                "geometry",
                f"snapshot mesh {m.get('mesh')} != configured {mesh}")
            return None, "geometry"
        snap_now = int(m.get("now", 0))
        shapes = session_shapes(self.dp.config)
        leading = () if mesh is None else (mesh["n_nodes"],)
        sessions: Dict[str, np.ndarray] = {}
        try:
            for table, fields in TABLE_COLS.items():
                tinfo = m["tables"][table]
                cb = int(tinfo["chunk_buckets"])
                arrs = {f: np.zeros(leading + shapes[f],
                                    SESSION_FIELDS[f])
                        for f in fields}
                for entry in tinfo["chunks"]:
                    block = self._read_chunk(entry, len(fields), cb,
                                             shapes[fields[0]][1])
                    if block is None:
                        self._count_restore(
                            "crc_mismatch",
                            f"chunk {entry['file']} failed verification")
                        return None, "crc_mismatch"
                    start = int(entry["start"])
                    for i, f in enumerate(fields):
                        dst = (arrs[f] if mesh is None
                               else arrs[f][int(entry["node"])])
                        dst[start:start + cb] = \
                            block[i].view(SESSION_FIELDS[f])
                sessions.update(arrs)
        except FileNotFoundError as e:
            self._count_restore("missing_chunk", str(e))
            return None, "missing_chunk"
        except Exception as e:  # noqa: BLE001 — clean refusal, never half
            self._count_restore("error", f"{type(e).__name__}: {e}")
            return None, "error"
        # rebase timestamps onto the new process's clock: ages are
        # preserved (time' = time - snap_now is <= 0, and the new
        # process's ticks start at 0), so idle-expiry semantics carry
        # straight across the restart
        for f in ("sess_time", "natsess_time"):
            sessions[f] = (
                sessions[f].astype(np.int64) - snap_now
            ).astype(np.int32)
        for f in SCALAR_FIELDS:
            v = m["scalars"].get(f, 0)
            sessions[f] = (np.asarray(v, np.int32) if mesh is not None
                           else np.int32(v if not isinstance(v, list)
                                         else v[0]))
        self._count_restore("restored")
        return sessions, "restored"

    def _read_chunk(self, entry: dict, n_cols: int, cb: int,
                    ways: int) -> Optional[np.ndarray]:
        """Read + verify one chunk file; None on any mismatch (torn
        header, truncated payload, CRC failure, manifest/file CRC
        disagreement)."""
        path = os.path.join(self.directory, entry["file"])
        want = n_cols * cb * ways * 4
        with open(path, "rb") as f:
            hdr = f.read(_HDR.size)
            if len(hdr) != _HDR.size:
                return None
            magic, crc, length = _HDR.unpack(hdr)
            if magic != _MAGIC or length != want or \
                    crc != int(entry["crc"]):
                return None
            payload = f.read(length + 1)
        if len(payload) != length or \
                (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return None
        return np.frombuffer(payload, np.int32).reshape(
            n_cols, cb, ways)

    def restore_into(self, dataplane=None) -> bool:
        """Restore the last generation into the dataplane's live
        epoch (via ``TableBuilder.to_device(sessions=...)`` — the swap
        carry-over contract). Returns True when the table came back
        warm; False means a clean cold start (reason in the restore
        outcome counter). Call right after the base-config swap and
        before traffic is offered."""
        dp = dataplane if dataplane is not None else self.dp
        sessions, outcome = self.restore()
        if sessions is None:
            return False
        dp.adopt_sessions(sessions)
        log.info("session table restored warm: generation %d (%s)",
                 self.stats["generation"], outcome)
        return True


# --- range-scoped drain/adopt (fleet live migration; ISSUE 18) -------
#
# The fleet steering tier (vpp_tpu/fleet/) moves session ownership
# between Dataplane instances in units of contiguous BUCKET RANGES —
# the same ranges its consistent hash steers flows by. A migration
# ships exactly the buckets whose hash range moved, nothing else:
# drain_bucket_range fetches them off the source (reusing the jitted
# chunk-drain program, so draining costs the same bounded device→host
# fetches a snapshot chunk does), adopt_bucket_range splices them into
# the destination's live columns with the snapshot-restore age rebase
# (time' = time − now_src + now_dst: idle AGES are preserved across
# instances whose tick clocks started at different walltimes), and
# release_bucket_range invalidates them on the source once ownership
# has flipped. Only the reflective "sess" table migrates: NAT sessions
# key on the post-NAT reply tuple, which the steering tier cannot hash
# direction-invariantly, so they cold-start on the new owner
# (docs/FLEET.md records the limitation).


def drain_bucket_range(dp, start: int, n_buckets: int,
                       table: str = "sess",
                       chunk_buckets: int = 256):
    """Fetch rows ``[start, start+n_buckets)`` of one session table as
    ``({field: host array [n, W]}, now_src)``. Reads ONE immutable
    epoch reference under the lock (the _drain consistency contract);
    the fetch itself runs outside it."""
    import jax

    fields = TABLE_COLS[table]
    with dp._lock:
        tables = dp.tables
        if tables is None:
            raise RuntimeError(
                "staging handle has no live tables to drain")
        now = max(dp._now, dp.clock_ticks())
    cols = tuple(getattr(tables, f) for f in fields)
    total = int(cols[0].shape[0])
    if not (0 <= start and n_buckets > 0
            and start + n_buckets <= total):
        raise ValueError(
            f"bucket range [{start}, {start + n_buckets}) outside "
            f"table of {total} buckets")
    cb = min(chunk_buckets, n_buckets)
    out = {f: [] for f in fields}
    fetch = _fetch_fn(cb)
    for off in range(start, start + n_buckets, cb):
        faults.fire("fleet.migrate")
        step = min(cb, start + n_buckets - off)
        block = np.asarray(jax.device_get(fetch(cols, np.int32(off))))
        count_device_transfer("migrate.drain", block)
        for i, f in enumerate(fields):
            out[f].append(block[i, :step].view(SESSION_FIELDS[f]))
    return ({f: np.concatenate(v, axis=0) for f, v in out.items()},
            int(now))


def adopt_bucket_range(dp, cols: Dict[str, np.ndarray], start: int,
                       now_src: int, table: str = "sess") -> int:
    """Splice migrated rows into the destination's live table at
    ``[start, start+n)``, age-rebased to the destination's clock, and
    publish (``adopt_sessions`` — the restore carry-over contract; the
    epoch bumps). Returns the count of live sessions adopted."""
    import jax

    fields = TABLE_COLS[table]
    n = int(next(iter(cols.values())).shape[0])
    with dp._lock:
        tables = dp.tables
        if tables is None:
            raise RuntimeError(
                "staging handle cannot adopt migrated sessions")
        now_dst = max(dp._now, dp.clock_ticks())
    sessions = {f: np.array(jax.device_get(getattr(tables, f)))
                for f in SESSION_FIELDS}
    count_device_transfer("migrate.adopt", sessions)
    total = int(sessions[fields[0]].shape[0])
    if not (0 <= start and n > 0 and start + n <= total):
        raise ValueError(
            f"bucket range [{start}, {start + n}) outside table of "
            f"{total} buckets")
    adopted = 0
    for f in fields:
        arr = np.asarray(cols[f], SESSION_FIELDS[f])
        if f.endswith("_time"):
            # the live-migration form of the restore rebase: ages are
            # preserved, so an entry idle-expired on the source stays
            # expired on the destination
            arr = (arr.astype(np.int64) - now_src
                   + now_dst).astype(np.int32)
        sessions[f][start:start + n] = arr
        if f.endswith("_valid"):
            adopted = int(arr.sum())
    dp.adopt_sessions(sessions)
    return adopted


def release_bucket_range(dp, start: int, n_buckets: int,
                         table: str = "sess") -> int:
    """Invalidate rows ``[start, start+n)`` on the SOURCE after its
    hash range moved away: the new owner serves them now, and a stale
    copy answering here would fork session state. Returns the count of
    live sessions released."""
    import jax

    valid_field = "sess_valid" if table == "sess" else "natsess_valid"
    with dp._lock:
        tables = dp.tables
        if tables is None:
            raise RuntimeError(
                "staging handle cannot release migrated sessions")
    sessions = {f: np.array(jax.device_get(getattr(tables, f)))
                for f in SESSION_FIELDS}
    count_device_transfer("migrate.release", sessions)
    total = int(sessions[valid_field].shape[0])
    if not (0 <= start and n_buckets > 0
            and start + n_buckets <= total):
        raise ValueError(
            f"bucket range [{start}, {start + n_buckets}) outside "
            f"table of {total} buckets")
    released = int(sessions[valid_field][start:start + n_buckets].sum())
    sessions[valid_field][start:start + n_buckets] = 0
    dp.adopt_sessions(sessions)
    return released
