"""Dataplane: the host-side handle on the device packet pipeline.

Owns the staging TableBuilder, the live DataplaneTables epoch, the
interface registry (pod ↔ interface index) and the jitted pipeline step.
Mutators stage changes in the builder; ``swap()`` publishes a new table
epoch atomically (carrying live session state over), the functional
analog of VPP's config transactions hitting the running graph.

Reference analogs: the vswitch side of plugins/contiv (interface
creation per pod) + vpp-agent applying NB config to VPP.
"""

from __future__ import annotations

import threading
import time as _time

import numpy as np
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from vpp_tpu.ir.rule import PodID
from vpp_tpu.pipeline.graph import (
    StepResult,
    make_pipeline_step,
)
from vpp_tpu.pipeline.tables import (
    DataplaneConfig,
    DataplaneTables,
    InterfaceType,
    TableBuilder,
)
from vpp_tpu.ops.vxlan import vxlan_encap
from vpp_tpu.pipeline.vector import Disposition, PacketVector
from vpp_tpu.trace import spans


def _packed_call(step, with_aux: bool = False, tel: str = "off"):
    """Wrap a pipeline step with a bit-packed IO boundary: ONE [5, B]
    int32 input and ONE [5, B] int32 output.

    ``with_aux=True`` additionally returns a ``[PACKED_AUX_ROWS]``
    int32 summary whose rows are named by ``PACKED_AUX_SCHEMA`` (the
    ONE schema constant every dispatch form — packed, chained, ring —
    derives its aux width from; widening the rider is an edit to that
    tuple plus one row expression below, never three hand-edited
    paths). Rows 3/4 sum the reflective + NAT tables, rows 5-7 are
    the per-packet ML stage's verdict counters (ISSUE 10), rows 8/9
    the device-telemetry counters (ISSUE 11). It rides the SAME
    device program and the same result fetch as the packed output
    (40 bytes, not a second round trip), so the pump can count
    fast-path batches, hit percentage, table congestion, ML verdicts
    and telemetry activity without widening the 20 B/packet boundary.

    ``tel`` (trace-time static — the step-factory gate of
    ops/telemetry.py) widens the call signature: below "off" the run
    is the classic ``(tables, flat, now)``; with telemetry on it is
    ``(tables, flat, now, rx_stamp, now_us)`` where ``rx_stamp`` is
    the batch's rx-enqueue microsecond stamp (the spare descriptor
    lane — 0 = unstamped, not observed) and ``now_us`` the dispatch
    clock; the wire latency ``now_us − rx_stamp`` is bucketed into
    the device-resident log2 histogram AFTER the step, inside the
    same program.

    Over a remote device transport (the axon tunnel) every host↔device
    transfer is a round trip; the unpacked path costs ~13 of them per
    frame (9 column uploads + 4 result fetches), which is what buried
    the r2 wire path at 0.001 Mpps. r3 packed that into one [9,B] up /
    one [10,B] down transfer; this layout additionally bit-packs the
    sub-32-bit header fields so the boundary is 20 B/packet each way
    instead of 36/40 — on a bandwidth-limited transport (the tunnel
    measures single-digit MB/s on bad days; PCIe DMA on real hardware)
    bytes-per-packet IS the wire-path throughput ceiling.

    Input rows (uint32 bit layout):
      0: src_ip            1: dst_ip
      2: sport<<16 | dport
      3: pkt_len<<16 | proto<<8 | ttl
      4: rx_if<<8 | flags
    Output rows:
      0: src_ip            1: dst_ip
      2: sport<<16 | dport
      3: drop_cause<<28 | disp<<24 | ttl<<16 | tx_if
         (tx_if 0xFFFF == none/-1; disp < 16, drop_cause = DROP_* < 16 —
         the spare high nibble carries the error-drop attribution so the
         host IO path can generate ICMP errors, graph.py DROP_*)
      4: next_hop
    proto and pkt_len are invariant through the pipeline (NAT rewrites
    addresses/ports, never protocol or length), so the tx side reuses
    the rx ring columns for them — they don't travel back.
    """

    def _core(tables, flat, now, rx_stamp, now_us):
        from jax import lax

        f = lax.bitcast_convert_type(flat, jnp.uint32)

        def i32(x):
            return x.astype(jnp.int32)

        pv = PacketVector(
            src_ip=f[0],
            dst_ip=f[1],
            proto=i32((f[3] >> 8) & 0xFF),
            sport=i32(f[2] >> 16),
            dport=i32(f[2] & 0xFFFF),
            ttl=i32(f[3] & 0xFF),
            pkt_len=i32(f[3] >> 16),
            rx_if=i32(f[4] >> 8),
            flags=i32(f[4] & 0xFF),
        )
        res = step(tables, pv, now)
        out_tables = res.tables
        tel_observed = jnp.int32(0)
        # jax-ok: tel is a trace-time-static step-factory gate (a
        # Python string baked into the jit key), not a tracer branch
        if tel != "off":
            from vpp_tpu.ops.telemetry import tel_latency_update

            # a zero stamp means "not stamped" (warm-up frames, ICMP
            # probes, chain padding); negative latency (clock wrap,
            # bogus stamp) is equally unobserved
            lat = now_us - rx_stamp
            observe = res.pkts.valid & (rx_stamp > 0) & (lat >= 0)
            out_tables, tel_observed = tel_latency_update(
                out_tables, observe,
                jnp.broadcast_to(lat, res.pkts.valid.shape))

        def u32(x):
            return x.astype(jnp.uint32)

        out = jnp.stack([
            res.pkts.src_ip,
            res.pkts.dst_ip,
            (u32(res.pkts.sport) << 16) | (u32(res.pkts.dport) & 0xFFFF),
            ((u32(res.drop_cause) & 0xF) << 28)
            | ((u32(res.disp) & 0xF) << 24)
            | ((u32(res.pkts.ttl) & 0xFF) << 16)
            | (u32(res.tx_if) & 0xFFFF),
            res.next_hop,
        ])
        packed = lax.bitcast_convert_type(out, jnp.int32)
        if with_aux:
            s = res.stats
            # row ORDER is PACKED_AUX_SCHEMA — keep the two in sync
            aux = jnp.stack([
                s.fastpath, s.rx, s.sess_hits,
                s.sess_insert_fail + s.natsess_insert_fail,
                (s.sess_evict_expired + s.sess_evict_victim
                 + s.natsess_evict_expired + s.natsess_evict_victim),
                s.ml_scored, s.ml_flagged, s.ml_drops,
                tel_observed, s.tel_sketched,
                s.tnt_limited, s.tnt_qfail,
            ]).astype(jnp.int32)
            return out_tables, packed, aux
        return out_tables, packed

    if tel == "off":
        # the pre-telemetry call signature: the off state adds no
        # arguments and no device work (the telemetry aux rows fold
        # to constants XLA keeps as two zero lanes of the rider)
        def run(tables, flat, now):
            return _core(tables, flat, now, jnp.int32(0), jnp.int32(0))

        return run
    return _core


def _chained_call(step, with_aux: bool = False, tel: str = "off"):
    """K packed steps in ONE device program: ``lax.scan`` over a
    [K, 5, B] stack of packed batches, session tables threaded
    batch-to-batch exactly as K separate dispatches would. One
    dispatch + one sync amortizes the per-step PJRT round trip
    (~100 µs locally, ~100 ms over the axon tunnel) across K frames —
    the 'K-chained device steps synced once' lever of docs/LATENCY.md
    (VERDICT r3 Next #4). Latency of the FIRST frame rises to the
    chain's span, so this serves throughput-with-bounded-sync, not
    single-frame latency. ``with_aux`` stacks the per-step
    [PACKED_AUX_ROWS] aux summaries into a [K, PACKED_AUX_ROWS] array
    next to the [K, 5, B] results. With ``tel`` on, the scan
    additionally carries per-sub-batch rx stamps ([K] int32 µs) and
    the dispatch clock, feeding the device latency histogram exactly
    like K separate packed dispatches would."""
    packed = _packed_call(step, with_aux=with_aux, tel=tel)

    def run_off(tables, flats, now):
        from jax import lax

        def body(tbl, flat):
            if with_aux:
                tbl2, out, aux = packed(tbl, flat, now)
                return tbl2, (out, aux)
            tbl2, out = packed(tbl, flat, now)
            return tbl2, out

        return lax.scan(body, tables, flats)

    def run_tel(tables, flats, now, rx_stamps, now_us):
        from jax import lax

        def body(tbl, xs):
            flat, stamp = xs
            if with_aux:
                tbl2, out, aux = packed(tbl, flat, now, stamp, now_us)
                return tbl2, (out, aux)
            tbl2, out = packed(tbl, flat, now, stamp, now_us)
            return tbl2, out

        return lax.scan(body, tables, (flats, rx_stamps))

    return run_off if tel == "off" else run_tel


# packed-boundary shape: [PACKED_IN_ROWS, B] in, [PACKED_OUT_ROWS_N, B] out
PACKED_IN_ROWS = 5
PACKED_OUT_ROWS_N = 5
# The aux-rider schema: row names of the per-batch int32 summary
# _packed_call(with_aux=True) returns, IN ORDER. This tuple is the ONE
# width authority for every dispatch form — packed, chained and the
# device-ring window program all derive their aux shape from it (and
# tests/test_telemetry.py pins all three against it), so widening the
# rider is an edit HERE plus the matching row expression in
# _packed_call, never three hand-edited paths. History: [3] (fastpath
# trio, PR 3) → [5] (+session pressure, PR 6) → [8] (+ML verdicts,
# PR 9) → [10] (+device telemetry, PR 10 / ISSUE 11) → [12]
# (+tenancy counters, ISSUE 14).
PACKED_AUX_SCHEMA = (
    "fastpath", "rx", "sess_hits",        # two-tier dispatch trio
    "insert_fails", "evictions",          # session-table pressure
    "ml_scored", "ml_flagged", "ml_drops",  # ML-stage verdicts
    "tel_observed", "tel_sketched",       # device telemetry (ISSUE 11)
    "tnt_limited", "tnt_qfail",           # tenancy (ISSUE 14): rate-
                                          # limit drops + slice quota
                                          # insert failures
)
PACKED_AUX_ROWS = len(PACKED_AUX_SCHEMA)


def _ring_call(step, slots: int, tel: str = "off"):
    """Device-resident descriptor-ring window program (ISSUE 7): ONE
    dispatch processes up to ``slots`` packed frames without any host
    callback in between.

    The host stages compacted [5, B] descriptors (20 B/packet, the
    ``_packed_call`` layout) into the slots of an rx ring window
    (io/rings.py DeviceDescRing) and ships the whole window as one
    transfer; on-device, a ``lax.while_loop`` polls the rx cursor
    against the shipped tail, runs the fused step per slot and appends
    the verdict descriptors + aux summaries to the device tx ring. The
    tx ring travels back in the window's ONE result fetch — the
    aux-rider pattern of PR 3/PR 6 generalized to the whole wire path —
    so the steady state of the persistent pump is io_callback-free:
    one host↔device exchange per window replaces the two ordered
    blocking callbacks per frame the r6 resident loop paid
    (pipeline/persistent.py holds the host half and the latency math).

    ``slots`` is config-static shape (``io.io_ring_slots``), part of
    the jit-cache key exactly like ``sess_ways`` rides the session
    arrays' shape. ``rx_now`` carries a per-slot timestamp so a window
    is bit-exact with the same frames issued as individual
    ``process_packed`` calls — the differential-test contract. The
    frame cursor is device-resident: it rides the window-to-window
    carry next to the session tables (the way sweep cursors ride the
    tables pytree), so consumed-frame accounting never costs a
    dedicated host sync.

    Signature (donations in the jit wrapper, ``_jitted_step``):
      (tables, cursor, rx_ring [S,5,B], rx_now [S], rx_tail) ->
      (tables', cursor + consumed, tx_ring [S,5,B],
       aux_ring [S, PACKED_AUX_ROWS])

    With ``tel`` on (ISSUE 11) the window additionally carries the
    per-slot rx-enqueue stamp lane ``rx_stamp [S]`` (µs — the pump
    stamps each frame at staging; one frame occupies one slot in
    persistent mode, so a slot-granular stamp IS per-frame) plus the
    dispatch clock ``now_us``; the program buckets each packet's
    ``now_us − rx_stamp`` into the device-resident latency histogram
    at tx-append, and the accumulated telemetry planes ride back as a
    widened aux rider (``pack_tel_rider``) in the window's ONE
    existing result fetch — ``io_callbacks`` stays 0 by construction:
      (tables, cursor, rx_ring, rx_now, rx_stamp [S], now_us,
       rx_tail) ->
      (tables', cursor + consumed, tx_ring, aux_ring,
       tel [tel_rider_width])
    """
    packed = _packed_call(step, with_aux=True, tel=tel)

    def _loop(tables, cursor, rx_ring, rx_now, rx_stamp, now_us,
              rx_tail):
        from jax import lax

        tx_ring0 = jnp.zeros_like(rx_ring)
        aux_ring0 = jnp.zeros((slots, PACKED_AUX_ROWS), jnp.int32)

        def cond(carry):
            _tables, head, _tx, _aux = carry
            return head < rx_tail

        def body(carry):
            tbl, head, tx, auxs = carry
            flat = lax.dynamic_index_in_dim(rx_ring, head, 0,
                                            keepdims=False)
            # jax-ok: tel is a trace-time-static step-factory gate
            if tel == "off":
                tbl2, out, aux = packed(tbl, flat, rx_now[head])
            else:
                tbl2, out, aux = packed(tbl, flat, rx_now[head],
                                        rx_stamp[head], now_us)
            tx = lax.dynamic_update_index_in_dim(tx, out, head, 0)
            auxs = lax.dynamic_update_index_in_dim(auxs, aux, head, 0)
            return tbl2, head + jnp.int32(1), tx, auxs

        tables, head, tx_ring, aux_ring = lax.while_loop(
            cond, body, (tables, jnp.int32(0), tx_ring0, aux_ring0))
        return tables, cursor + head, tx_ring, aux_ring

    if tel == "off":
        def run(tables, cursor, rx_ring, rx_now, rx_tail):
            return _loop(tables, cursor, rx_ring, rx_now, None,
                         jnp.int32(0), rx_tail)

        return run

    def run_tel(tables, cursor, rx_ring, rx_now, rx_stamp, now_us,
                rx_tail):
        from vpp_tpu.ops.telemetry import pack_tel_rider

        tables, cursor, tx_ring, aux_ring = _loop(
            tables, cursor, rx_ring, rx_now, rx_stamp, now_us, rx_tail)
        return tables, cursor, tx_ring, aux_ring, pack_tel_rider(tables)

    return run_tel


# Jitted step variants, shared PROCESS-WIDE across Dataplane instances
# (keyed by the selection gates + call form): make_pipeline_step is
# memoized so the underlying function identity is stable, and sharing
# the jit wrappers too means N dataplanes in one process (tests, the
# bench, multi-instance agents) compile each variant once.
_JIT_STEPS: Dict[tuple, object] = {}

# Runtime jit-compile guard (ISSUE 5 tentpole): every TRACE of a step
# variant is counted per (step key, argument-shape signature). A healthy
# process compiles each (impl, skip, fast, form, call-shape) exactly
# once; a count of 2+ IS the PR-4 regression class (a fresh-closure
# factory silently re-tracing per instance) happening live. Exported as
# ``vpp_tpu_jit_compiles_total{step=}`` (stats/collector.py), shown by
# `show io` and /debug/jit, enforced by the tests/conftest.py
# jit_compile_budget fixture and the end-of-session recompile check.
_JIT_COMPILES: Dict[tuple, int] = {}
_JIT_COMPILES_LOCK = threading.Lock()


def _step_label(impl: str, skip_local: bool, fast: bool, form: str,
                sweep_stride: int, ring_slots: int = 0,
                ml_mode: str = "off", ml_kind: str = "mlp",
                tel_mode: str = "off", tnt_mode: str = "off",
                fib_impl: str = "dense",
                sess_impl: str = "gather",
                sess_hash: str = "fwd",
                overlay: str = "off") -> str:
    from vpp_tpu.pipeline.graph import SWEEP_STRIDE_DEFAULT

    return "{}{}{}{}{}{}{}{}{}{}{}_{}".format(
        impl, "_nolocal" if skip_local else "", "_auto" if fast else "",
        ("" if ml_mode == "off"
         else f"_ml{ml_mode}"
         + ("_forest" if ml_kind == "forest" else "")),
        "" if tel_mode == "off" else f"_tel{tel_mode}",
        "" if tnt_mode == "off" else "_tenancy",
        "" if fib_impl == "dense" else f"_fib{fib_impl}",
        "" if sess_impl == "gather" else f"_sess{sess_impl}",
        "" if sess_hash == "fwd" else f"_h{sess_hash}",
        "" if overlay == "off" else f"_o{overlay}",
        ("" if sweep_stride == SWEEP_STRIDE_DEFAULT
         else f"_sw{sweep_stride}"),
        f"{form}{ring_slots}" if form == "ring" else form)


def _shape_sig(args, kwargs) -> tuple:
    def leaf_sig(x):
        shape = getattr(x, "shape", None)
        if shape is None:
            return type(x).__name__
        return (tuple(shape), str(getattr(x, "dtype", "?")))

    leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
    return tuple(leaf_sig(x) for x in leaves)


def _counting(label: str, fn):
    """Wrap ``fn`` so each TRACE (the python body running under jit —
    once per compile, never on cache hits) bumps the compile counter.
    Must wrap the OUTERMOST callable handed to jax.jit: an inner
    function can legitimately re-run within one compile (lax.scan
    traces its body twice), which would double-count."""

    def traced(*args, **kwargs):
        key = (label, _shape_sig(args, kwargs))
        with _JIT_COMPILES_LOCK:
            _JIT_COMPILES[key] = _JIT_COMPILES.get(key, 0) + 1
        return fn(*args, **kwargs)

    traced.__name__ = getattr(fn, "__name__", label)
    return traced


def jit_compile_counts() -> Dict[tuple, int]:
    """Snapshot of {(step label, shape signature): compile count}."""
    with _JIT_COMPILES_LOCK:
        return dict(_JIT_COMPILES)


def jit_compile_totals() -> Dict[str, int]:
    """Compiles per step label (the ``step=`` axis of
    ``vpp_tpu_jit_compiles_total``)."""
    totals: Dict[str, int] = {}
    with _JIT_COMPILES_LOCK:
        for (label, _sig), n in _JIT_COMPILES.items():
            totals[label] = totals.get(label, 0) + n
    return totals


def jit_recompiles() -> Dict[tuple, int]:
    """The violations: (step label, shape signature) keys traced more
    than once in this process. Non-empty == the compile-once contract
    is broken (tests/conftest.py fails the session on it)."""
    with _JIT_COMPILES_LOCK:
        return {k: n for k, n in _JIT_COMPILES.items() if n > 1}


class JitBudgetExceeded(AssertionError):
    """Raised by jit_compile_budget() when a scope compiles more step
    programs than it declared."""


class _JitBudget:
    def __init__(self, budget: int):
        self.budget = budget
        self._before: Optional[Dict[tuple, int]] = None

    def __enter__(self) -> "_JitBudget":
        self._before = jit_compile_counts()
        return self

    @property
    def spent(self) -> int:
        before = self._before or {}
        return (sum(jit_compile_counts().values())
                - sum(before.values()))

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            return
        before = self._before or {}
        after = jit_compile_counts()
        new = {k: n - before.get(k, 0) for k, n in after.items()
               if n - before.get(k, 0) > 0}
        spent = sum(new.values())
        if spent > self.budget:
            detail = ", ".join(
                f"{label}@{n}x" for (label, _sig), n in sorted(new.items()))
            raise JitBudgetExceeded(
                f"pipeline-step jit compile budget exceeded: {spent} "
                f"compiles > declared budget {self.budget} ({detail})")


def jit_compile_budget(budget: int) -> _JitBudget:
    """Context manager: fail if the enclosed scope triggers more than
    ``budget`` pipeline-step compiles. The pytest fixture of the same
    name (tests/conftest.py) wraps a whole test in one."""
    return _JitBudget(budget)


# Runtime device-transfer guard (ISSUE 20): the static ``--transfers``
# pass pins WHERE device->host fetches may happen (the manifest in
# tools/analysis/transfer_manifest.py); this counter proves HOW MUCH
# each approved site actually moves at run time. Every sanctioned fetch
# point funnels its device_get result through count_device_transfer(),
# keyed by site. Exported as
# ``vpp_tpu_device_transfer_bytes_total{site=}`` (stats/collector.py),
# shown by `show io`, enforced per-test by the opt-in transfer_budget
# fixture (tests/conftest.py), and recorded per bench section — the
# wire/persistent sections must fetch rider/descriptor bytes per
# window, never table columns ("~270 MB crosses the transport" was the
# PR-6/8/12 regression class).
_TRANSFER_BYTES: Dict[str, int] = {}
_TRANSFER_LOCK = threading.Lock()


def count_device_transfer(site: str, fetched) -> None:
    """Charge ``fetched``'s array bytes (any pytree of host/device
    arrays; scalars count their itemsize) to ``site``. Call it on the
    device_get RESULT at every approved fetch point — the charge is
    the bytes that actually crossed the transport."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(fetched):
        nb = getattr(leaf, "nbytes", None)
        total += int(nb) if nb is not None else 8
    with _TRANSFER_LOCK:
        _TRANSFER_BYTES[site] = _TRANSFER_BYTES.get(site, 0) + total


def device_transfer_totals() -> Dict[str, int]:
    """Snapshot of {site: device->host bytes fetched} this process
    (the ``site=`` axis of ``vpp_tpu_device_transfer_bytes_total``)."""
    with _TRANSFER_LOCK:
        return dict(_TRANSFER_BYTES)


class TransferBudgetExceeded(AssertionError):
    """Raised by transfer_budget() when a scope fetches more
    device->host bytes than it declared."""


class _TransferBudget:
    def __init__(self, budget_bytes: int):
        self.budget = budget_bytes
        self._before: Optional[Dict[str, int]] = None

    def __enter__(self) -> "_TransferBudget":
        self._before = device_transfer_totals()
        return self

    @property
    def spent(self) -> int:
        before = self._before or {}
        return (sum(device_transfer_totals().values())
                - sum(before.values()))

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            return
        before = self._before or {}
        after = device_transfer_totals()
        new = {k: n - before.get(k, 0) for k, n in after.items()
               if n - before.get(k, 0) > 0}
        spent = sum(new.values())
        if spent > self.budget:
            detail = ", ".join(
                f"{site}={n}B" for site, n in sorted(new.items()))
            raise TransferBudgetExceeded(
                f"device->host transfer budget exceeded: {spent} bytes "
                f"> declared budget {self.budget} ({detail})")


def transfer_budget(budget_bytes: int) -> _TransferBudget:
    """Context manager: fail if the enclosed scope fetches more than
    ``budget_bytes`` device->host bytes through the counted sites. The
    opt-in pytest fixture of the same name (tests/conftest.py) wraps a
    test declaring ``@pytest.mark.transfer_budget(n)``."""
    return _TransferBudget(budget_bytes)


def _jitted_step(impl: str, skip_local: bool, fast: bool, form: str,
                 sweep_stride: Optional[int] = None,
                 ring_slots: int = 0,
                 ml_mode: str = "off", ml_kind: str = "mlp",
                 tel_mode: str = "off", tnt_mode: str = "off",
                 fib_impl: str = "dense", sess_impl: str = "gather",
                 sess_hash: str = "fwd", overlay: str = "off"):
    from vpp_tpu.pipeline.graph import SWEEP_STRIDE_DEFAULT

    if sweep_stride is None:
        sweep_stride = SWEEP_STRIDE_DEFAULT
    if overlay != "off" and form != "plain":
        # The packed [5, B]/ring/chain boundaries carry no lane for the
        # overlay's inner-vector sidecar (or the outer result pair);
        # the overlay rides the plain step form only — the documented
        # CPU-harness caveat (docs/OVERLAY.md). Widening the packed
        # layout is future work, not a silent misdecode.
        raise ValueError(
            f"overlay={overlay!r} supports only the plain step form "
            f"(the packed/ring boundaries carry no inner-header "
            f"sidecar); got form {form!r}")
    key = (impl, skip_local, fast, form, sweep_stride, ring_slots,
           ml_mode, ml_kind, tel_mode, tnt_mode, fib_impl, sess_impl,
           sess_hash, overlay)
    step = _JIT_STEPS.get(key)
    if step is None:
        fn = make_pipeline_step(impl, skip_local, fast, sweep_stride,
                                ml_mode, ml_kind, tel_mode, tnt_mode,
                                fib_impl, sess_impl, sess_hash, overlay)
        label = _step_label(impl, skip_local, fast, form, sweep_stride,
                            ring_slots, ml_mode, ml_kind, tel_mode,
                            tnt_mode, fib_impl, sess_impl, sess_hash,
                            overlay)
        if form == "plain":
            step = jax.jit(_counting(label, fn))
        elif form == "packed":
            step = jax.jit(
                _counting(label, _packed_call(fn, with_aux=True,
                                              tel=tel_mode)),
                donate_argnums=(1,))
        elif form == "ring":
            # the device-ring window program: the WHOLE carry is
            # donated — tables (argnum 0; at the 10M-flow config a
            # non-donated carry would copy ~hundreds of MB of session
            # columns per window, where donation aliases the unchanged
            # config arrays and updates the session columns in place),
            # the window-to-window cursor scalar (argnum 1), and the
            # rx window (argnum 2, a fresh upload each dispatch,
            # donated so the tx ring reuses its HBM). The caller MUST
            # own the tables buffers it passes — PersistentPump copies
            # the dataplane's live tables once at start precisely so
            # the first window's donation can't invalidate buffers the
            # collector/CLI still read.
            step = jax.jit(
                _counting(label, _ring_call(fn, ring_slots,
                                            tel=tel_mode)),
                donate_argnums=(0, 1, 2))
        else:
            step = jax.jit(
                _counting(label, _chained_call(fn, with_aux=True,
                                               tel=tel_mode)),
                donate_argnums=(1,))
        _JIT_STEPS[key] = step
    return step


def packed_input_zeros(n: int):
    """An all-invalid packed input batch (flags=0) — the pre-compile /
    warm-up argument for ``process_packed``."""
    return np.zeros((PACKED_IN_ROWS, n), np.int32)


def pack_packet_columns(fu, cols, n: int, off: int = 0) -> None:
    """Pack ring columns (native/ring.py PV_COLUMNS views) into a packed
    input batch. ``fu`` is the uint32 view of a [5, B] int32 batch;
    writes packets [off, off+n)."""
    def u(name):
        return cols[name][:n].view(np.uint32)

    fu[0, off:off + n] = u("src_ip")
    fu[1, off:off + n] = u("dst_ip")
    fu[2, off:off + n] = (u("sport") << 16) | (u("dport") & 0xFFFF)
    fu[3, off:off + n] = (
        ((u("pkt_len") & 0xFFFF) << 16) | ((u("proto") & 0xFF) << 8)
        | (u("ttl") & 0xFF)
    )
    fu[4, off:off + n] = (u("rx_if") << 8) | (u("flags") & 0xFF)


def unpack_packet_input(flat) -> dict:
    """Host-side inverse of ``pack_packet_columns``: decode a [5, B]
    packed input batch back into named PacketVector column arrays (the
    pump's tracing path runs the unpacked step from these)."""
    fu = flat.view(np.uint32)
    return {
        "src_ip": fu[0],
        "dst_ip": fu[1],
        "proto": ((fu[3] >> 8) & 0xFF).astype(np.int32),
        "sport": (fu[2] >> 16).astype(np.int32),
        "dport": (fu[2] & 0xFFFF).astype(np.int32),
        "ttl": (fu[3] & 0xFF).astype(np.int32),
        "pkt_len": (fu[3] >> 16).astype(np.int32),
        "rx_if": (fu[4] >> 8).astype(np.int32),
        "flags": (fu[4] & 0xFF).astype(np.int32),
    }


def unpack_packet_result(out) -> dict:
    """Decode a fetched [5, B] packed result into named host arrays.
    ``out`` must be a writable int32 array (np.array of the device_get).
    tx_if 0xFFFF decodes to -1 (no egress interface)."""
    assert out.shape[0] == PACKED_OUT_ROWS_N, out.shape
    ou = out.view(np.uint32)
    row3 = ou[3]
    tx_if = (row3 & 0xFFFF).astype(np.int32)
    tx_if[tx_if == 0xFFFF] = -1
    return {
        "src_ip": ou[0],
        "dst_ip": ou[1],
        "sport": (ou[2] >> 16).astype(np.int32),
        "dport": (ou[2] & 0xFFFF).astype(np.int32),
        "ttl": ((row3 >> 16) & 0xFF).astype(np.int32),
        "disp": ((row3 >> 24) & 0xF).astype(np.int32),
        "drop_cause": (row3 >> 28).astype(np.int32),
        "tx_if": tx_if,
        "next_hop": ou[4],
    }


class Dataplane:
    def __init__(
        self, config: Optional[DataplaneConfig] = None, materialize: bool = True
    ):
        """``materialize=False`` skips the initial device upload — used by
        ClusterDataplane, which stages through per-node builders but
        publishes node-stacked tables itself (parallel/cluster.py)."""
        self.config = config or DataplaneConfig()
        self.builder = TableBuilder(self.config)
        self.tables: Optional[DataplaneTables] = (
            self.builder.to_device() if materialize else None
        )
        self.epoch = 0
        self._lock = threading.RLock()
        # Guards a whole stage-mutate-then-swap commit sequence. For a
        # standalone dataplane it's the same lock; a ClusterDataplane
        # repoints every node handle at ITS lock so one node's commit
        # can't publish another node's half-applied staging (cluster
        # swap reads all builders). Writers (renderer commit, CNI server,
        # node events, service configurator) hold this across builder
        # mutations + swap().
        self.commit_lock = self._lock
        # Step variants are built lazily through ONE factory
        # (graph.make_pipeline_step), jit-cached PROCESS-WIDE per
        # (classifier impl, skip-local, fast-tier, call form) — see
        # _get_step / _jitted_step. The two-tier (fast) variants put
        # BOTH kernels —
        # the classify-free fast path and the full chain — behind a
        # lax.cond in one jitted program, so an epoch swap caches both
        # compilations exactly like the plain step (jit keys on shapes,
        # which are epoch-invariant; only the selection gates flip).
        # Packed/chained forms donate the packed input (in and out are
        # both [5, B] int32, so XLA aliases the buffers) and ALL carry
        # the aux summary — the plain chain reports fastpath=0 but
        # still measures rx/sess_hits, so the hit-percentage regime
        # signal exists even with the fast path disengaged (exactly
        # when an operator is deciding whether to enable it).
        self._encap = None  # jitted vxlan_encap, built on first use
        # Classifier selection (re-evaluated at every swap, like the
        # fast-path gate): the ``classifier`` knob picks
        # dense | mxu | bv | auto; auto ladders BV above bv_min_rules
        # (the memory cap is honored at builder allocation —
        # ops/acl_bv.bv_enabled_for), MXU above mxu_threshold, dense
        # below. ``_use_mxu`` is kept as the legacy boolean view of
        # the selection (impl == "mxu").
        self.classifier = getattr(self.config, "classifier", "auto")
        self.mxu_threshold = 512
        self.bv_min_rules = int(
            getattr(self.config, "classifier_bv_min_rules", 1024)
        )
        self._classifier_impl = "dense"
        self._use_mxu = False
        # Policy-free local-classify skip: when NO interface points at
        # a local ACL table at swap time, the compiled step elides the
        # local stage entirely (ops/acl.acl_local_none) — gathering
        # full [P, R] rule rows against an all-(-1) if_local_table was
        # pure waste on nodes without isolated pods.
        self._skip_local = True
        # Established-flow fast path (two-tier dispatch). The enable +
        # min-rules threshold come from DataplaneConfig (YAML:
        # dataplane.fastpath / dataplane.fastpath_min_rules);
        # ``_use_fastpath`` is re-evaluated at every swap() against the
        # staged global rule count, like the classifier selection.
        self.fastpath_enabled = bool(getattr(self.config, "fastpath", True))
        self.fastpath_min_rules = int(
            getattr(self.config, "fastpath_min_rules", 0)
        )
        self._use_fastpath = False
        # Per-packet ML scoring stage (ISSUE 10; ops/mlscore.py): the
        # configured mode (off | score | enforce) engages only once a
        # model is actually staged (builder.set_ml_model) — re-gated
        # at every swap like the classifier/fastpath selections, so a
        # score/enforce config with no model compiles the stage OUT
        # and scoring starts at the first model-publishing swap.
        self.ml_stage = getattr(self.config, "ml_stage", "off")
        self._ml_mode = "off"
        self._ml_kind = "mlp"
        # Device-resident telemetry plane (ops/telemetry.py; ISSUE 11):
        # a pure config gate — unlike the classifier/ml selections it
        # never re-gates at swap (there is no staged state to consult;
        # the planes' shapes are config-static like sess_ways).
        self._tel_mode = getattr(self.config, "telemetry", "off")
        # Multi-tenant gateway mode (vpp_tpu/tenancy/; ISSUE 14): a
        # pure config gate like telemetry — the tenant planes' shapes
        # are config-static, and an unconfigured tenancy-on dataplane
        # behaves exactly like off (single default tenant, unsliced,
        # unlimited), so there is no staged state to re-gate on.
        self._tnt_mode = getattr(self.config, "tenancy", "off")
        # FIB lookup implementation (ISSUE 15; ops/fib.py dense,
        # ops/lpm.py per-length binary search): the classifier-ladder
        # twin — ``fib_impl: auto`` engages LPM once the staged route
        # count reaches fib_lpm_min_routes (and the staged table fits
        # its planes — builder.lpm_ok()), re-gated at every swap.
        self.fib_impl_knob = getattr(self.config, "fib_impl", "auto")
        self.fib_lpm_min_routes = int(
            getattr(self.config, "fib_lpm_min_routes", 256))
        self._fib_impl = "dense"
        # Session-probe implementation (ISSUE 16; ops/session.py
        # gather rung vs the fused pallas probe): eligibility is pure
        # backend + VMEM-budget — no staged state — but the selection
        # is re-derived with the rest so `show kernels` reads one
        # coherent snapshot.
        self.session_impl_knob = getattr(self.config, "session_impl",
                                         "auto")
        self._session_impl = "gather"
        # Session bucket hash family (tables.py sess_hash; ISSUE 18):
        # a pure config gate like telemetry — "sym" buckets flows
        # direction-invariantly so the fleet steering tier can map
        # packets to bucket ranges from outside the dataplane.
        self._sess_hash = getattr(self.config, "sess_hash", "fwd")
        # Device-resident VXLAN overlay stage pair (ISSUE 19): a pure
        # config gate like telemetry — the svc/overlay planes are
        # config-static shapes, and an overlay-on dataplane with no
        # VTEP/VNI staged only fail-closes overlay-ADDRESSED frames
        # (UDP:4789), so there is no staged state to re-gate on. ONE
        # extra step-form dimension, plain form only (_jitted_step).
        self._overlay = getattr(self.config, "overlay", "off")
        # optional Prometheus histogram (stats/collector.py): observes
        # the fib-group upload cost of every swap that actually
        # re-shipped FIB state (vpp_tpu_fib_churn_commit_seconds)
        self.fib_churn_hist = None
        self._refresh_selection()
        # diagnostic classify-probe accumulators (time_classifier):
        # exported as the stage="classify" row of the
        # vpp_tpu_pump_stage_seconds family and shown by `show acl`
        self.classify_seconds = 0.0
        self.classify_ns_pkt: Optional[float] = None
        self._classify_probe_cache: Dict[str, object] = {}
        # Session time base: wall-clock ticks (TICKS_PER_SEC), not frame
        # counts — aging semantics must not depend on offered load
        # (VERDICT r1 Weak #5; the reference ages on timers).
        self._t0 = _time.monotonic()
        self._now = 0
        # Amortized session aging (ops/session.py session_sweep): the
        # fused step sweeps this many buckets per table per step
        # (trace-time static — part of the jit-cache key).
        self._sweep_stride = int(
            getattr(self.config, "sess_sweep_stride", 256))
        # steps dispatched since the last expire_sessions() — the
        # lazy-maintenance signal (in-step sweep coverage)
        self._steps_since_expire = 0

        # interface registry
        self.pod_if: Dict[PodID, int] = {}
        self.if_pod: Dict[int, PodID] = {}
        self._free_ifs = list(range(self.config.max_ifaces - 1, 0, -1))
        # if 0 stays reserved as "unset"; uplink/host claimed explicitly
        self.uplink_if: Optional[int] = None
        self.host_if: Optional[int] = None

        # ACL table slot registry (renderer table id -> slot)
        self.table_slots: Dict[str, int] = {}
        self._free_slots = list(range(self.config.max_tables - 1, -1, -1))
        # optional PacketTracer (vpp_tpu.trace); when set, every
        # processed frame is offered to it (captures only while armed)
        self.tracer = None
        # optional TxnJournal (pipeline/txn.py): with enable_journal(),
        # every epoch swap records the builder ops staged since the
        # previous swap — the api-trace analog for the LIVE agent
        # (VERDICT r3 Missing #3)
        self.journal = None
        # observers notified when a pod interface slot is freed (the
        # statscollector zeroes its accumulators so a later pod reusing
        # the slot doesn't inherit counters)
        self.on_if_freed = []
        # optional Prometheus histograms (stats/collector.py
        # register_control_plane_metrics): txn_commit_hist observes
        # every swap's publish duration; propagation_hist observes the
        # config-propagation SLO (config event wall-clock → epoch-swap
        # complete) whenever a swap publishes under an active span trace
        self.txn_commit_hist = None
        self.propagation_hist = None

    # --- interfaces ---
    def add_uplink(self) -> int:
        with self._lock:
            if self.uplink_if is None:
                self.uplink_if = self._free_ifs.pop()
                self.builder.set_interface(
                    self.uplink_if, InterfaceType.UPLINK, apply_global=True
                )
            return self.uplink_if

    def add_host_interface(self) -> int:
        with self._lock:
            if self.host_if is None:
                self.host_if = self._free_ifs.pop()
                self.builder.set_interface(self.host_if, InterfaceType.HOST)
            return self.host_if

    def add_pod_interface(self, pod: PodID) -> int:
        with self._lock:
            if pod in self.pod_if:
                return self.pod_if[pod]
            if not self._free_ifs:
                raise RuntimeError("interface table full")
            idx = self._free_ifs.pop()
            self.pod_if[pod] = idx
            self.if_pod[idx] = pod
            self.builder.set_interface(idx, InterfaceType.POD)
            return idx

    def del_pod_interface(self, pod: PodID) -> bool:
        with self._lock:
            idx = self.pod_if.pop(pod, None)
            if idx is None:
                return False
            del self.if_pod[idx]
            self.builder.set_interface(idx, InterfaceType.NONE, local_table=-1)
            self._free_ifs.append(idx)
            observers = list(self.on_if_freed)
        for cb in observers:
            cb(idx)
        return True

    # --- ACL table slots (used by the TPU renderer) ---
    def alloc_table_slot(self, table_id: str) -> int:
        with self._lock:
            if table_id in self.table_slots:
                return self.table_slots[table_id]
            if not self._free_slots:
                raise RuntimeError("ACL table slots exhausted")
            slot = self._free_slots.pop()
            self.table_slots[table_id] = slot
            return slot

    def free_table_slot(self, table_id: str) -> None:
        with self._lock:
            slot = self.table_slots.pop(table_id, None)
            if slot is not None:
                self.builder.clear_local_table(slot)
                self._free_slots.append(slot)

    def assign_pod_table(self, pod: PodID, table_id: Optional[str]) -> None:
        """Point the pod's interface at a local ACL table (or none)."""
        with self._lock:
            idx = self.pod_if.get(pod)
            if idx is None:
                return
            slot = self.table_slots.get(table_id, -1) if table_id else -1
            self.builder.set_if_local_table(idx, slot)

    # --- epoch management ---
    def enable_journal(self, path: Optional[str]) -> None:
        """Turn on the config transaction trace: builder mutations are
        recorded and journaled (JSONL at ``path``; None = in-memory
        count only) per epoch swap. Replaying the journal onto a fresh
        builder reproduces the exact table history this dataplane
        enforced (reference: contiv-vswitch.conf `api-trace { on }`)."""
        from vpp_tpu.pipeline.txn import TxnJournal

        with self._lock:
            self.journal = TxnJournal(path)
            self.builder.start_recording()

    def swap(self) -> int:
        """Publish the staged configuration as a new table epoch. Live
        session state is carried over from the running epoch.

        On a cluster-node staging handle the swap delegates to the owning
        ClusterDataplane (set via ``_swap_delegate``), so renderers and
        the CNI server drive cluster nodes unchanged."""
        delegate = getattr(self, "_swap_delegate", None)
        span = spans.RECORDER.begin("swap", "epoch-swap")
        try:
            if delegate is not None:
                # cluster-node staging handle: the owning
                # ClusterDataplane publishes the multi-chip epoch; the
                # span + histograms still record THIS commit's cost and
                # propagation as the caller experienced it
                epoch = delegate()
                span.attrs["epoch"] = epoch
                span.name = f"epoch {epoch} (cluster)"
            else:
                with self._lock:
                    if self.tables is None:
                        raise RuntimeError(
                            "this Dataplane has no live tables and no "
                            "swap delegate (materialize=False without a "
                            "managing ClusterDataplane)"
                        )
                    self.tables = self.builder.to_device(
                        sessions=self.tables)
                    # re-gate the classifier selection, the policy-free
                    # local skip and the two-tier dispatch on the new
                    # epoch's staged state (the variants stay
                    # jit-cached — shapes are epoch-invariant, only the
                    # gates flip)
                    self._refresh_selection()
                    if (self.fib_churn_hist is not None
                            and self.builder.fib_last_shipped):
                        # route-churn commit cost (ISSUE 15): only
                        # swaps that actually re-shipped FIB state
                        self.fib_churn_hist.observe(
                            float(self.builder.fib_upload.get(
                                "ms", 0.0)) / 1e3)
                    self.epoch += 1
                    span.attrs["epoch"] = self.epoch
                    span.name = f"epoch {self.epoch}"
                    if self.journal is not None:
                        txn = self.builder.drain_recording()
                        if txn is not None:
                            self.journal.record(txn, self.epoch)
                    epoch = self.epoch
        finally:
            # the enclosing trace's root (KSR event, CNI add, ...) holds
            # the config event timestamp; capture it before this span
            # pops in case the swap IS the root (then there is no
            # propagation to measure — a bare swap isn't an NB event)
            root = spans.current_root()
            spans.RECORDER.end(span)
        if self.txn_commit_hist is not None and span.done:
            self.txn_commit_hist.observe(span.duration)
        if (self.propagation_hist is not None and root is not None
                and root is not span):
            self.propagation_hist.observe(
                _time.time() - root.t_wall, source=root.stage
            )
        return epoch

    def adopt_sessions(self, sessions) -> int:
        """Publish restored session state into the live tables (the
        crash-consistent snapshot restore path, pipeline/snapshot.py).

        ``sessions`` is a ``{field: host array}`` mapping of
        SESSION_FIELDS; the upload routes through
        ``TableBuilder.to_device(sessions=...)`` so it follows the same
        carry-over contract as an epoch swap (shape validation, config
        groups served from the device cache — nothing but the session
        columns ships). The epoch bumps so a persistent-mode pump
        restarts its resident ring against the restored state. Call at
        agent start, right after the base-config swap and before
        traffic — the builder must hold no unpublished staging (this
        path would publish it early)."""
        with self._lock:
            if self.tables is None:
                raise RuntimeError(
                    "this Dataplane is a staging handle managed by a "
                    "ClusterDataplane; session restore is not supported "
                    "on cluster node handles")
            self.tables = self.builder.to_device(sessions=sessions)
            self.epoch += 1
            return self.epoch

    # --- VXLAN edge (cluster-boundary peers; TPU↔TPU rides ICI instead) ---
    def set_vtep(self, vtep_ip: int) -> None:
        """Set this node's VXLAN tunnel endpoint address (the reference's
        per-node vxlanCIDR IP, plugins/contiv/ipam computeVxlanIPAddress).
        Also stages the device-resident copy (``ovl_vtep_ip``) the fused
        overlay stage pair reads (ISSUE 19) — published at the next
        swap(), like every staged mutation."""
        with self._lock:
            self._vtep = jnp.uint32(vtep_ip)
            self.builder.set_vtep_ip(vtep_ip)

    def encap_remote(self, result: StepResult) -> PacketVector:
        """Outer-header vector for REMOTE-disposed packets of a step —
        the vxlan-encap graph node for traffic leaving the cluster edge."""
        vtep = getattr(self, "_vtep", None)
        if vtep is None:
            raise RuntimeError("set_vtep() before encap_remote()")
        if self._encap is None:
            self._encap = jax.jit(vxlan_encap)
        # Encap only REMOTE traffic with a VTEP next_hop (fabric peers
        # and edge peers with an explicit tunnel endpoint): routes with
        # next_hop 0 — e.g. the SNAT'd default route — leave as plain IP
        # out the uplink; encapping them would emit VXLAN toward dst 0.
        mask = (result.disp == int(Disposition.REMOTE)) & (result.next_hop != 0)
        return self._encap(result.pkts, mask, vtep, result.next_hop)

    # --- time base (VPP session/NAT timers analog) ---
    TICKS_PER_SEC = 10

    def clock_ticks(self) -> int:
        """Monotonic wall-clock ticks since this dataplane started."""
        return int((_time.monotonic() - self._t0) * self.TICKS_PER_SEC)

    def advance_clock(self, seconds: float) -> None:
        """Shift the time base forward (tests simulate idle periods
        without sleeping)."""
        self._t0 -= seconds

    # --- session aging (host reclamation; lookups already ignore expired
    # entries and inserts evict them — the in-step sweep is the
    # steady-state reclaimer, this is the on-demand bulk pass) ---
    def expire_sessions(self, max_age: Optional[int] = None,
                        lazy: bool = False) -> int:
        """Invalidate reflective + NAT sessions idle for more than
        ``max_age`` ticks (default: the configured sess_max_age).
        Returns the number of sessions expired.

        ``lazy=True`` is the periodic-maintenance form: when the
        in-step amortized sweep (ops/session.py session_sweep) has
        covered the whole table since the last call — i.e. steps x
        stride >= buckets — the bulk device pass is SKIPPED, because
        steady-state aging already happened inside the fused program.
        Idle nodes (no steps) and tiny tables still reclaim here, so
        the occupancy gauges never go stale."""
        from vpp_tpu.ops.session import session_expire

        if max_age is None:
            max_age = self.config.sess_max_age
        with self._lock:
            if self.tables is None:
                return 0
            # the lazy skip is sound only for the CONFIGURED timeout:
            # the in-step sweep enforces tables.sess_max_age, so a
            # caller-supplied shorter max_age must still run the bulk
            # pass (it reclaims entries the sweep deliberately keeps)
            if lazy and max_age == self.config.sess_max_age:
                steps = self._steps_since_expire
                self._steps_since_expire = 0
                from vpp_tpu.ops.session import sweep_covered

                if sweep_covered(steps, self._sweep_stride, self.tables):
                    return 0
            self._now = max(self._now, self.clock_ticks())
            before = self.tables
            after = session_expire(before, self._now, max_age)
            # transfer-ok: device-reduced scalar (expired-slot count)
            expired = int(
                jnp.sum(before.sess_valid - after.sess_valid)
                + jnp.sum(before.natsess_valid - after.natsess_valid)
            )
            # publish ONLY when something expired: a no-op replacement
            # would still invalidate the `tables is self.tables` guard
            # of a concurrently dispatched step and silently discard
            # that batch's session inserts (the maintenance loop runs
            # every few seconds against live traffic)
            if expired:
                self.tables = after
        return expired

    # --- classifier / step selection ---
    @property
    def classifier_impl(self) -> str:
        """The global-classify implementation the LIVE epoch runs
        ("dense" | "mxu" | "bv") — surfaced by `show acl` and the
        ``vpp_tpu_acl_classifier`` info gauge."""
        return self._classifier_impl

    @property
    def fib_impl(self) -> str:
        """The ip4-lookup implementation the LIVE epoch runs ("dense" |
        "lpm" | "pallas") — surfaced by `show fib` and the
        ``vpp_tpu_fib_impl`` info gauge (ISSUE 15/16)."""
        return self._fib_impl

    @property
    def session_impl(self) -> str:
        """The session-probe implementation the LIVE epoch runs
        ("gather" | "pallas") — surfaced by `show kernels` and the
        ``vpp_tpu_kernel_impl`` info gauge (ISSUE 16)."""
        return self._session_impl

    def kernel_snapshot(self) -> dict:
        """Per-op kernel-rung resolution behind `show kernels` and the
        ``vpp_tpu_kernel_impl`` info-gauge family: which rung each hot
        op's ladder selected, the operator's knob, and WHY (the
        eligibility bit that decided). One coherent read under the
        lock — the StepStats ↔ Prometheus parity discipline."""
        from vpp_tpu.ops._pallas import pallas_available, use_pallas
        from vpp_tpu.ops.session import session_pallas_fits

        with self._lock:
            b = self.builder
            p_ok = use_pallas()

            def why(impl, knob, eligible, reason_ineligible):
                if impl == "pallas":
                    return "tpu backend + structure eligible"
                if knob == impl:
                    return "explicit knob"
                if not p_ok:
                    return "no tpu backend (pallas rung needs one)"
                if not eligible:
                    return reason_ineligible
                return "ladder heuristic"

            return {
                "backend": jax.default_backend(),
                "pallas_available": pallas_available(),
                "classifier": {
                    "impl": self._classifier_impl,
                    "knob": self.classifier,
                    "why": why(self._classifier_impl, self.classifier,
                               b.bv_ok(),
                               "bv structure ineligible"),
                },
                "fib": {
                    "impl": self._fib_impl,
                    "knob": self.fib_impl_knob,
                    "why": why(self._fib_impl, self.fib_impl_knob,
                               b.lpm_ok(),
                               "lpm planes ineligible"),
                },
                "session": {
                    "impl": self._session_impl,
                    "knob": self.session_impl_knob,
                    "why": why(self._session_impl,
                               self.session_impl_knob,
                               session_pallas_fits(self.config),
                               "table exceeds VMEM budget"),
                },
            }

    def fib_snapshot(self) -> Optional[dict]:
        """Host scalars behind `show fib` / the ``vpp_tpu_fib_*``
        families: live route count, per-length histogram, ECMP group
        registry + the per-member forwarded-packet plane ([G, W] ints
        cross the transport, never route columns), plane bytes and the
        last churn upload. In persistent pump mode the ECMP plane
        rides the ring's private carry, so its view refreshes at
        sync_sessions/stop — the `show sessions` staleness contract."""
        from vpp_tpu.ops.lpm import lpm_plane_bytes

        with self._lock:
            t = self.tables
            b = self.builder
            # histogram straight off the per-slot arrays: correct for
            # dense-only configs too (the LPM staging counters only
            # move while planes are allocated)
            live = b.fib_plen[b.fib_plen >= 0]
            cnts = np.bincount(live, minlength=33) if len(live) else []
            by_len = {int(L): int(n) for L, n in enumerate(cnts) if n}
            # per-member rows aggregated ONCE here — `show fib` and
            # the vpp_tpu_fib_ecmp_packets family both consume these,
            # so the two views can never diverge
            groups = {}
            for g, e in b.nh_groups.items():
                groups[g] = [
                    {"nh": int(m[0]), "tx_if": int(m[1]),
                     "node": int(m[2]),
                     "ways": [w for w, a in enumerate(e["assign"])
                              if a == m],
                     "pkts": 0}
                    for m in e["members"]
                ]
            snap = {
                "impl": self._fib_impl,
                "knob": self.fib_impl_knob,
                "routes": int(len(live)),
                "by_length": by_len,
                "lpm_ok": b.lpm_ok(),
                "lpm_build_ms": float(b.lpm_build_ms),
                "ecmp_groups": groups,
                "plane_bytes": lpm_plane_bytes(self.config),
                "upload": dict(b.fib_upload),
            }
        if t is not None:
            ecmp_c = np.asarray(jax.device_get(t.fib_ecmp_c), np.int64)
            count_device_transfer("fib.snapshot", ecmp_c)
            snap["ecmp_c"] = ecmp_c
            for g, members in groups.items():
                for m in members:
                    if m["ways"]:
                        m["pkts"] = int(ecmp_c[g, m["ways"]].sum())
        return snap

    def _select_classifier(self) -> str:
        """Resolve the ``classifier`` knob against the staged builder
        state — eligibility bits (range rules for MXU, non-prefix
        masks or a busted memory cap for BV) feed the ONE shared
        ladder (partition.select_impl), which the cluster and
        multi-host planes apply to their own agreed bits so the mesh
        can never silently select a different rung."""
        from vpp_tpu.ops._pallas import use_pallas
        from vpp_tpu.parallel.partition import select_impl

        b = self.builder
        return select_impl(self.classifier, b.bv_ok(),
                           b.mxu_enabled and b.glb_mxu.ok,
                           b.glb_nrules, self.bv_min_rules,
                           self.mxu_threshold, pallas_ok=use_pallas())

    def _refresh_selection(self) -> None:
        """Re-gate every per-epoch compile-time choice against the
        staged builder: classifier impl, the policy-free local-classify
        skip, and the fast-path engagement. Called from __init__ and
        under the lock at every swap()."""
        b = self.builder
        self._classifier_impl = self._select_classifier()
        self._use_mxu = self._classifier_impl == "mxu"
        self._skip_local = bool((b.if_local_table < 0).all())
        self._use_fastpath = (
            self.fastpath_enabled
            and b.glb_nrules >= self.fastpath_min_rules
        )
        # ML stage engages only with a model staged (kind != NONE);
        # the staged model's kind picks the compiled kernel variant
        ml_kind = int(getattr(b, "ml_kind", 0))
        self._ml_mode = self.ml_stage if ml_kind else "off"
        self._ml_kind = "forest" if ml_kind == 2 else "mlp"
        # FIB ladder (ISSUE 15): lpm when eligible and big enough —
        # the ONE shared rung mapping (partition.select_fib_impl), so
        # a mesh plane adopting the ladder can never diverge
        from vpp_tpu.ops._pallas import use_pallas
        from vpp_tpu.ops.session import session_pallas_fits
        from vpp_tpu.parallel.partition import (
            select_fib_impl,
            select_session_impl,
        )

        p_ok = use_pallas()
        self._fib_impl = select_fib_impl(
            self.fib_impl_knob, b.lpm_ok(), b.fib_route_count(),
            self.fib_lpm_min_routes, pallas_ok=p_ok)
        self._session_impl = select_session_impl(
            self.session_impl_knob,
            p_ok and session_pallas_fits(self.config))

    def _get_step(self, fast: bool, form: str = "plain"):
        """The jit-cached step variant of the current selection.
        ``form``: "plain" (PacketVector in/out), "packed" ([5, B]
        boundary + aux) or "chain" (K packed frames under lax.scan).
        Call under ``_lock`` (reads the selection gates).

        The local-skip gate is an OPTIMIZATION, never a requirement:
        the non-skip variant is correct for every epoch (interfaces
        with if_local_table == -1 are permitted by the local stage
        anyway), so when that variant is already built we keep using
        it rather than paying a second full-chain compile for the
        skip variant — a process oscillating between policy-free and
        policied epochs compiles ONE program, whichever came first."""
        skip = self._skip_local
        stride = self._sweep_stride
        gates = (self._ml_mode, self._ml_kind, self._tel_mode,
                 self._tnt_mode, self._fib_impl, self._session_impl,
                 self._sess_hash, self._overlay)
        if (skip
                and (self._classifier_impl, skip, fast, form, stride,
                     0) + gates not in _JIT_STEPS
                and (self._classifier_impl, False, fast, form, stride,
                     0) + gates in _JIT_STEPS):
            skip = False
        return _jitted_step(self._classifier_impl, skip, fast, form,
                            stride, ml_mode=self._ml_mode,
                            ml_kind=self._ml_kind,
                            tel_mode=self._tel_mode,
                            tnt_mode=self._tnt_mode,
                            fib_impl=self._fib_impl,
                            sess_impl=self._session_impl,
                            sess_hash=self._sess_hash,
                            overlay=self._overlay)

    def time_classifier(self, batch: int = 256, iters: int = 10) -> float:
        """Diagnostic: time the SELECTED global classifier in isolation
        over a synthetic batch and return ns/packet. Accumulates wall
        seconds into ``classify_seconds`` (exported as the
        stage="classify" row of ``vpp_tpu_pump_stage_seconds``) and
        records ``classify_ns_pkt`` for `show acl`. Not hot-path work —
        the first call per impl pays a jit compile; bench/operator use."""
        from vpp_tpu.pipeline.graph import _classifier_fns
        from vpp_tpu.pipeline.vector import make_packet_vector

        with self._lock:
            if self.tables is None:
                raise RuntimeError("no live tables to time against")
            tables = self.tables
            impl = self._classifier_impl
        fn = self._classify_probe_cache.get(impl)
        if fn is None:
            fn = jax.jit(_classifier_fns(impl)[0])
            self._classify_probe_cache[impl] = fn
        uplink = self.uplink_if if self.uplink_if is not None else 0
        pkts = make_packet_vector(
            [{"src": "172.16.0.9", "dst": "10.1.1.2", "proto": 6,
              "sport": 40000 + i, "dport": 8000 + (i % 20),
              "rx_if": uplink} for i in range(min(batch, 64))],
            n=batch,
        )
        jax.block_until_ready(fn(tables, pkts).permit)  # compile+warm
        t0 = _time.perf_counter()
        for _ in range(iters):
            out = fn(tables, pkts)
        jax.block_until_ready(out.permit)
        dt = _time.perf_counter() - t0
        self.classify_seconds += dt
        self.classify_ns_pkt = dt / iters / batch * 1e9
        return self.classify_ns_pkt

    # --- traffic ---
    def _pick_step(self):
        """The unpacked step for the current regime: the two-tier auto
        dispatcher when the fast path is engaged, else the plain chain
        (classifier impl and local-skip per the epoch's selection
        either way). Call under ``_lock``."""
        return self._get_step(self._use_fastpath, "plain")

    def process(self, pkts: PacketVector, now: Optional[int] = None,
                ovl_inner: Optional[PacketVector] = None,
                ovl_vni=None) -> StepResult:
        """Run one packet vector through the fused step. With the
        overlay on (``config.overlay: vxlan``), ``ovl_inner``/
        ``ovl_vni`` are the host-IO-parsed inner-header sidecar for
        VXLAN-framed ingress ([P] inner PacketVector + [P] int32 VNI,
        -1 = no VXLAN framing on that lane); None synthesizes the
        all-unframed sidecar, under which any overlay-ADDRESSED frame
        fails closed (DROP_OVERLAY) — exactly what an unparseable
        VXLAN frame must do."""
        with self._lock:
            if self.tables is None:
                raise RuntimeError(
                    "this Dataplane is a staging handle managed by a "
                    "ClusterDataplane; process frames via cluster.step()"
                )
            tables = self.tables
            step = self._pick_step()
            self._steps_since_expire += 1
            if now is None:
                # wall-clock ticks, monotone non-decreasing (max keeps
                # explicitly-supplied test timestamps from going backward)
                self._now = max(self._now, self.clock_ticks())
                now = self._now
        if self._overlay != "off":
            if ovl_vni is None:
                ovl_vni = jnp.full(pkts.valid.shape, -1, jnp.int32)
            if ovl_inner is None:
                ovl_inner = pkts
            result = step(tables, pkts, jnp.int32(now), ovl_inner,
                          jnp.asarray(ovl_vni, jnp.int32))
        else:
            result = step(tables, pkts, jnp.int32(now))
        # Session-table mutations flow back into the live epoch (config
        # arrays are identical between result.tables and the staged ones
        # unless a swap happens, which re-grafts the session arrays).
        with self._lock:
            if tables is self.tables:
                self.tables = result.tables
            tracer = self.tracer
        if tracer is not None:
            tracer.record(result)
        return result

    def probe(self, pkts: PacketVector, now: Optional[int] = None) -> StepResult:
        """Side-effect-free step: classify a synthetic frame against the
        LIVE tables without committing anything back — no reflective
        session is installed, no tracer fires, no counters move. Debug
        probes (`test connectivity`) must never open a return-traffic
        hole or consume session slots."""
        with self._lock:
            if self.tables is None:
                raise RuntimeError(
                    "this Dataplane is a staging handle managed by a "
                    "ClusterDataplane; probe via its node pipelines"
                )
            tables = self.tables
            step = self._get_step(fast=False)
            if now is None:
                now = max(self._now, self.clock_ticks())
        if self._overlay != "off":
            return step(tables, pkts, jnp.int32(now), pkts,
                        jnp.full(pkts.valid.shape, -1, jnp.int32))
        return step(tables, pkts, jnp.int32(now))

    def process_packed(self, flat, now: Optional[int] = None,
                       commit: bool = True, with_aux: bool = False,
                       stamp_us: int = 0,
                       now_us: Optional[int] = None):
        """Single-transfer variant of process() for the pump's hot path:
        ``flat`` is a host [5, B] int32 bit-packed batch (see
        ``_packed_call`` for the row layout; build with
        ``pack_packet_columns`` / ``packed_input_zeros``); returns the
        DEVICE [5, B] int32 packed result without forcing a host sync —
        the caller device_gets it when ready. One upload, one fetch per
        batch, 20 bytes per packet each way.

        ``with_aux=True`` returns ``(out, aux)`` instead, where ``aux``
        is the DEVICE [8] int32 summary
        ``[fastpath, rx, sess_hits, insert_fails, evictions,
        ml_scored, ml_flagged, ml_drops]`` from the
        same program. It is
        measured on BOTH tiers (fastpath is 0 on the full chain), so
        the session-hit regime signal exists even with the fast path
        disengaged.

        ``commit=False`` discards the resulting session-table state (a
        probe-like classify): REQUIRED for any caller other than the
        pump's single dispatch thread — two concurrent committers race
        the ``tables is self.tables`` swap guard and one side's
        reflective-session installs would be silently lost.

        With telemetry on (``config.telemetry`` != off), ``stamp_us``
        is the batch's rx-enqueue microsecond stamp (ops/telemetry.py
        tel_clock_us; 0 = unstamped, not observed) and ``now_us`` the
        dispatch clock (None = read it here) — the device histograms
        ``now_us − stamp_us`` for every valid packet inside the same
        program."""
        with self._lock:
            if self.tables is None:
                raise RuntimeError(
                    "this Dataplane is a staging handle managed by a "
                    "ClusterDataplane; process frames via cluster.step()"
                )
            tables = self.tables
            step = self._get_step(self._use_fastpath, "packed")
            if commit:
                self._steps_since_expire += 1
            if now is None:
                self._now = max(self._now, self.clock_ticks())
                now = self._now
        if self._tel_mode != "off":
            from vpp_tpu.ops.telemetry import tel_clock_us

            if now_us is None:
                now_us = tel_clock_us()
            new_tables, out, aux = step(
                tables, jnp.asarray(flat), jnp.int32(now),
                jnp.int32(stamp_us), jnp.int32(now_us))
        else:
            new_tables, out, aux = step(tables, jnp.asarray(flat),
                                        jnp.int32(now))
        if commit:
            with self._lock:
                if tables is self.tables:
                    self.tables = new_tables
        return (out, aux) if with_aux else out

    def process_packed_chain(self, flats, now: Optional[int] = None,
                             with_aux: bool = False,
                             stamps_us=None,
                             now_us: Optional[int] = None):
        """K packed batches in ONE device dispatch (``_chained_call``):
        ``flats`` is a host [K, 5, B] int32 stack; returns the DEVICE
        [K, 5, B] packed results. One dispatch + one fetch for K
        frames — the bounded-sync throughput lever when per-step
        dispatch dominates (remote transports, small frames).
        ``with_aux=True`` returns ``(outs, auxs)`` with the stacked
        [K, PACKED_AUX_ROWS] aux summaries (measured on both tiers).
        ``stamps_us`` ([K] int32 µs rx-enqueue stamps) feeds the
        device latency histogram when telemetry is on (None = all
        unstamped)."""
        with self._lock:
            if self.tables is None:
                raise RuntimeError(
                    "this Dataplane is a staging handle managed by a "
                    "ClusterDataplane; process frames via cluster.step()"
                )
            tables = self.tables
            step = self._get_step(self._use_fastpath, "chain")
            # a K-chain sweeps once per scanned sub-batch
            self._steps_since_expire += max(1, len(flats))
            if now is None:
                self._now = max(self._now, self.clock_ticks())
                now = self._now
        if self._tel_mode != "off":
            from vpp_tpu.ops.telemetry import tel_clock_us

            if now_us is None:
                now_us = tel_clock_us()
            if stamps_us is None:
                stamps_us = np.zeros(len(flats), np.int32)
            new_tables, (outs, auxs) = step(
                tables, jnp.asarray(flats), jnp.int32(now),
                jnp.asarray(stamps_us, jnp.int32), jnp.int32(now_us))
        else:
            new_tables, (outs, auxs) = step(
                tables, jnp.asarray(flats), jnp.int32(now)
            )
        with self._lock:
            if tables is self.tables:
                self.tables = new_tables
        return (outs, auxs) if with_aux else outs

    # --- device telemetry (ops/telemetry.py; ISSUE 11) ---
    def telemetry_snapshot(self) -> Optional[dict]:
        """Host copy of the collect-facing telemetry planes: latency
        bins, the sketched-packet scalar and the top-K candidate rows.
        A few hundred BYTES cross the transport — the [d, w] sketch
        matrix stays device-resident (the PR 6 `show sessions` rule:
        collect fetches scalars, never tables). None when telemetry is
        off or no tables are live. Persistent-mode callers prefer the
        pump's rider snapshot (DataplanePump.tel_snapshot) — the ring
        threads its tables privately, so dp.tables lags until
        stop/sync."""
        if self._tel_mode == "off":
            return None
        with self._lock:
            t = self.tables
        if t is None:
            return None
        bins, sketched, key, src, dst, ports, cnt = jax.device_get((
            t.tel_lat_hist, t.tel_sketched, t.tel_top_key,
            t.tel_top_src, t.tel_top_dst, t.tel_top_ports,
            t.tel_top_cnt))
        count_device_transfer(
            "telemetry.snapshot",
            (bins, sketched, key, src, dst, ports, cnt))
        return {
            "mode": self._tel_mode,
            "bins": np.asarray(bins, np.int64),
            "sketched": int(sketched),
            "top_key": np.asarray(key, np.uint32),
            "top_src": np.asarray(src, np.uint32),
            "top_dst": np.asarray(dst, np.uint32),
            "top_ports": np.asarray(ports, np.uint32),
            "top_cnt": np.asarray(cnt, np.int64),
        }

    # --- multi-tenant gateway mode (vpp_tpu/tenancy/; ISSUE 14) ---
    def tenant_snapshot(self) -> Optional[dict]:
        """Host copy of the per-tenant planes `show tenants` and the
        ``vpp_tpu_tenant_*`` families read: token-bucket levels,
        rx/goodput/drop/quota-fail counters, and per-tenant live
        session occupancy (one on-device prefix sum —
        tenancy/derive.py tenant_occupancy; [T] ints cross the
        transport, never columns). None when tenancy is off or no
        tables are live. In persistent pump mode the planes ride the
        ring's private carry, so this view refreshes at
        sync_sessions/stop — the `show sessions` staleness contract.
        """
        if self._tnt_mode == "off":
            return None
        with self._lock:
            t = self.tables
            now = max(self._now, self.clock_ticks())
            registry = {tid: dict(e)
                        for tid, e in self.builder.tenants.items()}
        if t is None:
            return None
        from vpp_tpu.tenancy.derive import tenant_occupancy

        occ = tenant_occupancy(t.sess_valid, t.sess_time,
                               jnp.int32(now), t.sess_max_age,
                               t.tnt_sess_base, t.tnt_sess_mask + 1)
        tokens, rx, tx, rl, qf, occ_h, rate, burst, smask = \
            jax.device_get((t.tnt_tokens, t.tnt_rx_c, t.tnt_tx_c,
                            t.tnt_rl_c, t.tnt_qf_c, occ, t.tnt_rate,
                            t.tnt_burst, t.tnt_sess_mask))
        count_device_transfer(
            "tenant.snapshot",
            (tokens, rx, tx, rl, qf, occ_h, rate, burst, smask))
        return {
            "tenants": registry,
            "tokens": np.asarray(tokens, np.int64),
            "rx": np.asarray(rx, np.int64),
            "tx": np.asarray(tx, np.int64),
            "rl_drops": np.asarray(rl, np.int64),
            "quota_fails": np.asarray(qf, np.int64),
            "occupancy": np.asarray(occ_h, np.int64),
            "rate": np.asarray(rate, np.int64),
            "burst": np.asarray(burst, np.int64),
            "sess_quota_slots": (np.asarray(smask, np.int64) + 1)
            * int(getattr(self.config, "sess_ways", 4)),
        }
