"""Persistent device loop: ONE resident program pumps many frames.

The last lever of docs/LATENCY.md (VERDICT r3 Next #4): instead of one
PJRT dispatch per frame (~100 µs locally, ~100 ms over a remote
transport, paid per frame), a single jitted ``lax.while_loop`` stays
RESIDENT on the device and exchanges packed frames with the host
through ordered ``io_callback``s — the host feeds a refill queue, the
device loop fetches/processes/delivers without ever returning to the
dispatch path. VPP analog: the eternal graph dispatch loop of a worker
thread, vs issuing one `vlib_main` per frame.

Per-frame cost inside the loop = host handoff + pipeline compute; the
dispatch/trace/donation machinery is paid ONCE at loop start. The
trade: the device is synchronously coupled to the host callbacks
(an empty refill queue blocks the device program), so this serves the
latency-floor regime — a node wanting minimum added latency per frame
— not peak batch throughput, which the pipelined/chained paths own.

Control protocol (host -> device via the fetched control word):
  >= 0: a frame follows in the same fetch — process it
  STOP: exit the while_loop and return the final session tables
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import io_callback

from vpp_tpu.pipeline.dataplane import (
    PACKED_IN_ROWS,
    _packed_call,
)
from vpp_tpu.pipeline.graph import make_pipeline_step

STOP = np.int32(-1)


class PersistentPump:
    """Host side of the resident loop: feed/collect packed frames.

    One instance drives one device program invocation; ``submit()``
    hands a [5, B] packed frame to the loop, ``results`` yields
    [5, B] packed outputs in order. ``stop()`` makes the device loop
    exit and the driver thread return the final tables.

    ``fastpath=True`` (default) runs the two-tier auto dispatcher
    inside the resident loop: an all-established frame takes the
    classify-free kernel — the latency-floor regime is exactly where
    steady-state return traffic lives, so the resident loop benefits
    the most. Each delivered frame carries its [5] aux summary
    (``[fastpath, rx, sess_hits, insert_fails, evictions]``) through
    the same ordered deliver
    callback; ``result_ex()`` exposes it, ``result()`` drops it.

    ``classifier``/``skip_local`` mirror the owning Dataplane's epoch
    selection (pipeline/graph.py make_pipeline_step), so the resident
    loop's full-chain tier classifies exactly like the dispatch path
    would — the pump re-creates the loop on every epoch swap, which is
    when the selection can flip.
    """

    def __init__(self, tables, batch: int, max_frames: int = 1 << 20,
                 fastpath: bool = True, classifier: str = "dense",
                 skip_local: bool = False,
                 sweep_stride: Optional[int] = None):
        from vpp_tpu.pipeline.graph import SWEEP_STRIDE_DEFAULT

        self.batch = int(batch)
        self.fastpath_enabled = bool(fastpath)
        self._in: "queue.Queue" = queue.Queue()
        self._out: "queue.Queue" = queue.Queue()
        self._tables_final = None
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._max_frames = max_frames
        self._tables0 = tables
        if sweep_stride is None:
            sweep_stride = SWEEP_STRIDE_DEFAULT
        step_fn = make_pipeline_step(classifier, skip_local,
                                     fast=fastpath,
                                     sweep_stride=sweep_stride)
        # aux always on: the plain chain reports fastpath=0, so the
        # deliver callback keeps ONE shape either way
        self._step = _packed_call(step_fn, with_aux=True)

        self._stop_seen = False

        def host_fetch(_tick):
            """Ordered callback: block until the host has a frame (or
            stop); returns (ctl, frame)."""
            item = self._in.get()
            if item is None:
                self._stop_seen = True
                return STOP, np.zeros(
                    (PACKED_IN_ROWS, self.batch), np.int32)
            return np.int32(item[0]), item[1]

        def host_deliver(out_frame, aux):
            self._out.put((np.asarray(out_frame), np.asarray(aux)))
            return np.int32(0)

        fetch_shape = (
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((PACKED_IN_ROWS, self.batch), jnp.int32),
        )
        deliver_shape = jax.ShapeDtypeStruct((), jnp.int32)

        def loop(tables):
            def cond(carry):
                tables_, i, stopped = carry
                return (~stopped) & (i < self._max_frames)

            def body(carry):
                tables_, i, _ = carry
                ctl, flat = io_callback(host_fetch, fetch_shape, i,
                                        ordered=True)
                stopped = ctl < 0

                def run(t):
                    t2, out, aux = self._step(t, flat, ctl)
                    _ = io_callback(host_deliver, deliver_shape, out,
                                    aux, ordered=True)
                    return t2

                tables2 = lax.cond(stopped, lambda t: t, run, tables_)
                return tables2, i + 1, stopped

            final, _, _ = lax.while_loop(
                cond, body, (tables, jnp.int32(0), jnp.bool_(False)))
            return final

        # jax-ok: one resident loop per pump BY DESIGN — the loop closes
        # over this instance's rings/queues, and a process runs one
        # long-lived pump (the compile is the pump's startup cost)
        self._loop = jax.jit(loop)

    # --- lifecycle ---
    def start(self) -> "PersistentPump":
        def drive():
            try:
                self._tables_final = jax.block_until_ready(
                    self._loop(self._tables0))
                if not self._stop_seen:
                    # the loop exhausted max_frames mid-stream: later
                    # submits would hang their consumers silently
                    self._error = RuntimeError(
                        f"persistent loop frame budget "
                        f"({self._max_frames}) exhausted without stop")
            except BaseException as e:  # noqa: BLE001 — re-raised to
                # the caller from result()/stop(); a silently dead
                # loop would leave result() blocking to timeout
                self._error = e

        self._thread = threading.Thread(target=drive, daemon=True,
                                        name="persistent-pump")
        self._thread.start()
        return self

    def _check_error(self) -> None:
        if self._error is not None:
            raise RuntimeError("persistent loop died") from self._error

    def submit(self, flat: np.ndarray, now: int) -> None:
        """Queue one packed [5, B] frame; ``now`` rides the control
        word (must be >= 0). The frame is COPIED — callers may reuse
        their staging buffer immediately."""
        assert now >= 0
        self._check_error()
        self._in.put((now, np.array(flat, np.int32, copy=True)))

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.result_ex(timeout=timeout)[0]

    def result_ex(self, timeout: Optional[float] = None):
        """Like result(), but returns ``(out, aux)`` where ``aux`` is
        the frame's [5] int32 summary
        ``[fastpath, rx, sess_hits, insert_fails, evictions]`` (the
        pump's regime + session-pressure telemetry)."""
        try:
            return self._out.get(timeout=timeout)
        except queue.Empty:
            self._check_error()  # surface the REAL cause if the loop died
            raise

    def stop(self, join_timeout: float = 60.0):
        """Exit the device loop; returns the final session tables."""
        self._in.put(None)
        self._thread.join(timeout=join_timeout)
        if self._thread.is_alive():
            raise RuntimeError("persistent loop did not exit")
        self._check_error()
        return self._tables_final
