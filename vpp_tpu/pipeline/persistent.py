"""Persistent device pump over device-resident descriptor rings.

ISSUE 7 tentpole. The r6 persistent mode kept ONE resident
``lax.while_loop`` on the device but fed it through TWO ordered
``io_callback`` host round trips per frame (fetch + deliver) — each a
blocking device↔host synchronization, which is why BENCH_r05 measured
the daemon persistent path at 61.7% goodput with a 52 ms pump p99
while the same transport's transfer ceiling sat at 76.9 Mpps. nanoPU's
reflex-plane framing (PAPERS.md) is the latency model: the NIC-to-
compute path must not bounce through the host per frame.

This rework makes the steady state io_callback-free:

  * the host (stager thread) writes compacted ~20 B/packet descriptors
    into a pinned staging window (io/rings.py DeviceDescRing) and
    ships the WHOLE window with one transfer — the dispatch of the
    jitted window program (pipeline/dataplane.py ``_ring_call``);
  * on-device, a ``lax.while_loop`` polls the rx cursor against the
    shipped tail, runs the fused step per slot, and appends verdict
    descriptors + aux summaries to the device tx ring;
  * the tx ring rides back in the window's ONE result fetch (fetcher
    thread) — the aux-rider pattern generalized to the wire path — and
    with the double-buffered windows the fetch of window N overlaps
    the staging + dispatch of window N+1. The frame cursor and the
    session tables thread window-to-window as a device-resident carry,
    so per-frame accounting never costs a host sync.

Per frame in steady state: 1/S of a dispatch + 1/S of a fetch (S =
``io_ring_slots``), zero host callbacks — vs 2 blocking callbacks per
frame before. Window fill is adaptive: a lone frame dispatches in a
1-slot window (the latency floor is preserved), a backlog fills the
window before dispatch (throughput). The window program compiles ONCE
process-wide through the ``_jitted_step`` cache — an epoch-swap
restart of the pump re-uses the compiled program, where the r6 loop
paid a fresh per-instance jit every restart.

``stats["io_callbacks"]`` counts host callback invocations made by the
device program. The ring design makes none — the counter exists so a
regression reintroducing a callback into the steady state is a
measured fact (`io_wire_callbacks_per_window` in bench.py), not prose.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from vpp_tpu.io.rings import DESC_ROWS, DeviceDescRing
from vpp_tpu.pipeline.dataplane import (
    PACKED_IN_ROWS,
    _jitted_step,
    count_device_transfer,
)
from vpp_tpu.testing import faults

assert DESC_ROWS == PACKED_IN_ROWS, (
    "io/rings.py DESC_ROWS must track pipeline.dataplane.PACKED_IN_ROWS"
)

STOP = np.int32(-1)  # legacy control word (kept for API compat)

_SENTINEL = object()


class PersistentPump:
    """Host side of the device-ring persistent path.

    API is unchanged from the r6 resident loop: ``submit()`` hands a
    [5, B] packed frame in, ``result_ex()`` yields ``(out, aux)`` per
    frame in submission order, ``stop()`` flushes everything in flight
    and returns the final session tables. Internally, submitted frames
    are staged into descriptor-ring windows and exchanged with the
    device one window at a time (module doc).

    ``fastpath``/``classifier``/``skip_local``/``sweep_stride``/
    ``ml_mode``/``ml_kind`` mirror
    the owning Dataplane's epoch selection exactly as before — the
    window program is fetched from the process-wide ``_jitted_step``
    cache keyed on them plus the ring geometry, so a pump restart
    (epoch swap) never recompiles.

    ``ring_slots`` frames per window and ``ring_windows`` staging
    buffers (>= 2: the double buffer that overlaps window N's
    writeback with window N+1's refill) are config-static shape —
    ``io.io_ring_slots`` / ``io.io_ring_windows``.
    """

    def __init__(self, tables, batch: int, max_frames: int = 1 << 20,
                 fastpath: bool = True, classifier: str = "dense",
                 skip_local: bool = False,
                 sweep_stride: Optional[int] = None,
                 ring_slots: int = 8, ring_windows: int = 2,
                 ml_mode: str = "off", ml_kind: str = "mlp",
                 tel_mode: str = "off", tnt_mode: str = "off",
                 sess_hash: str = "fwd"):
        self.batch = int(batch)
        self.fastpath_enabled = bool(fastpath)
        self.ring = DeviceDescRing(slots=ring_slots, batch=self.batch,
                                   windows=ring_windows)
        # latency-governor actuator (ISSUE 13; io/governor.py): the
        # stager closes a window once it holds this many slots, even
        # with more backlog queued — the host-side window-shaping
        # lever between the 1-slot lone-frame floor and the S-slot
        # backlog fill. Written by the owning pump's dispatch thread,
        # read by the stager: a plain int (GIL-atomic), no lock —
        # and NOT part of the window program's inputs beyond the
        # already-dynamic slot count `n`, so governing never retraces.
        self._fill_limit = self.ring.slots
        self._in: "queue.Queue" = queue.Queue()
        # dispatched windows awaiting their result fetch, in dispatch
        # order: (widx, n_frames, tx_ring, aux_ring) device futures
        self._fetch_q: "queue.Queue" = queue.Queue()
        self._out: "queue.Queue" = queue.Queue()
        self._tables0 = tables
        self._tables_pending = None
        self._tables_final = None
        # set by the owning DataplanePump (under ITS stats lock) once
        # this ring's counters have been folded into its accumulator —
        # a concurrent stats sync then must not count them again
        self.retired = False
        self._error: Optional[BaseException] = None
        self._threads: list = []
        self._max_frames = max_frames  # legacy knob; windows need no budget
        # telemetry gate (ops/telemetry.py; ISSUE 11): with it on, the
        # window program takes the per-slot stamp lane + dispatch
        # clock and returns the packed telemetry rider as a 5th output
        # riding the window's one result fetch
        self._tel = tel_mode
        self._step = _jitted_step(classifier, skip_local, fast=fastpath,
                                  form="ring", sweep_stride=sweep_stride,
                                  ring_slots=self.ring.slots,
                                  ml_mode=ml_mode, ml_kind=ml_kind,
                                  tel_mode=tel_mode, tnt_mode=tnt_mode,
                                  sess_hash=sess_hash)
        # device-resident frame cursor, threaded window-to-window next
        # to the tables (the sweep-cursor pattern); fetched only by
        # stats()/stop, never per window
        self._cursor0 = jnp.int32(0)
        # stager writes windows_dispatched, fetcher writes the rest —
        # one lock serializes the counters and the snapshot
        self._stats_lock = threading.Lock()
        self.stats = {
            # windows fully exchanged (dispatched AND written back)
            "ring_windows": 0,
            # frames staged through the ring (fill telemetry: frames
            # vs windows*slots is the window-fill ratio `show io`
            # derives)
            "ring_frames": 0,
            "windows_dispatched": 0,
            # priority-lane preemptions (ISSUE 13): windows the stager
            # shipped EARLY because a priority slot landed (the lane's
            # bounded-queueing mechanism — a reflex frame never waits
            # for the backlog to drain into its window). Folded into
            # the owning pump's ring accumulator across restarts.
            "priority_preempts": 0,
            # host callback invocations by the device program — the
            # ring steady state makes NONE (module doc). Any future
            # callback added to the window program MUST route its
            # host function through a counter bump here; the lowered-
            # program check (tests/test_device_rings.py
            # TestCallbackFreeProgram) is what actually catches a
            # callback sneaking in without one.
            "io_callbacks": 0,
        }
        # latest telemetry rider (fetcher-written under _stats_lock):
        # the raw int32 vector of pack_tel_rider, cumulative — the
        # owning pump unpacks it with the config geometry
        self._tel_last: Optional[np.ndarray] = None

    # --- lifecycle ---
    def start(self) -> "PersistentPump":
        for fn, name in ((self._stage_loop, "persistent-stage"),
                         (self._fetch_loop, "persistent-fetch")):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def _check_error(self) -> None:
        if self._error is not None:
            raise RuntimeError("persistent loop died") from self._error

    @property
    def failed(self) -> bool:
        """True once either ring thread has died. The owning pump's
        dispatch loop polls this between bursts so a death with no
        pending submit still counts toward the ring-fault fallback
        (a wedged ring must not hide behind an idle rx queue)."""
        return self._error is not None

    def submit(self, flat: np.ndarray, now: int,
               stamp_us: int = 0, priority: bool = False) -> None:
        """Queue one packed [5, B] frame; ``now`` is its per-slot
        timestamp (must be >= 0) and ``stamp_us`` its rx-enqueue
        microsecond stamp for the wire-latency histogram (0 =
        unstamped; ignored with telemetry off). ``priority`` marks a
        reflex-lane frame (ISSUE 13): the stager ships its window the
        moment the slot lands instead of draining the backlog into it.
        The frame is COPIED — callers may reuse their staging buffer
        immediately."""
        assert now >= 0
        self._check_error()
        self._in.put((int(now), int(stamp_us),
                      np.array(flat, np.int32, copy=True),
                      bool(priority)))

    def set_fill_limit(self, n_slots: int) -> None:
        """Governor actuator: cap the stager's window fill at
        ``n_slots`` (clamped to [1, ring slots]). Host-side only —
        the window program's slot count is already a dynamic input,
        so no jit variant is touched."""
        self._fill_limit = max(1, min(int(n_slots), self.ring.slots))

    def fill_avg(self, last: Optional[tuple] = None):
        """``(snapshot, avg_fill)`` where ``snapshot`` is the ring's
        cumulative ``(windows, slots)`` pair and ``avg_fill`` the
        average slots per window SINCE ``last`` (None until a window
        shipped in the delta) — the governor's occupancy input."""
        snap = self.ring.fill_snapshot()
        w0, s0 = last if last is not None else (0, 0)
        dw, ds = snap[0] - w0, snap[1] - s0
        return snap, (ds / dw if dw > 0 else None)

    def checkpoint_sessions(self, timeout: float = 30.0):
        """Consistent DEVICE COPY of the in-ring session state, taken
        by the stager BETWEEN windows (the ring threads its tables
        privately and donates them window-to-window, so an outside
        reader can neither see them nor safely hold a reference — a
        copy at a window boundary is the only coherent read). The
        crash-consistent snapshotter's freshness hook
        (io/pump.py sync_sessions): without it, a long-lived ring
        would leave dp.tables frozen at launch state and every
        interval snapshot would capture stale sessions against an
        advancing clock. Returns a {field: device array} dict of
        SESSION_FIELDS, or None when the ring is stopping/dead or the
        wait times out (callers skip the sync — no worse than the
        pre-hook behavior)."""
        if self._error is not None:
            return None
        ev = threading.Event()
        box: dict = {}
        self._in.put(("ckpt", ev, box))
        if not ev.wait(timeout):
            return None
        return box.get("sessions")

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self.result_ex(timeout=timeout)[0]

    def result_ex(self, timeout: Optional[float] = None):
        """Like result(), but returns ``(out, aux)`` where ``aux`` is
        the frame's [8] int32 summary
        ``[fastpath, rx, sess_hits, insert_fails, evictions,
        ml_scored, ml_flagged, ml_drops]`` (the pump's regime,
        session-pressure and ML-marking telemetry)."""
        try:
            return self._out.get(timeout=timeout)
        except queue.Empty:
            self._check_error()  # surface the REAL cause if the loop died
            raise

    def stats_snapshot(self) -> dict:
        """Consistent copy of the ring counters plus the live overlap
        occupancy (in-flight windows, writeback lag). Host scalars
        only — nothing crosses the device transport."""
        with self._stats_lock:
            s = dict(self.stats)
        s["ring_inflight"] = self.ring.in_flight()
        s["ring_lag"] = s.pop("windows_dispatched") - s["ring_windows"]
        return s

    def tel_raw(self) -> Optional[np.ndarray]:
        """Latest telemetry rider (raw ``pack_tel_rider`` int32
        vector; cumulative) — None until the first telemetry-on window
        wrote back. The owning pump unpacks it against the config
        geometry (ops/telemetry.py unpack_tel_rider)."""
        with self._stats_lock:
            tel = self._tel_last
        return None if tel is None else tel.copy()

    def stop(self, join_timeout: float = 60.0):
        """Flush every queued frame through the device and return the
        final session tables."""
        self._in.put(None)
        for t in self._threads:
            t.join(timeout=join_timeout)
            if t.is_alive():
                raise RuntimeError("persistent loop did not exit")
        self._check_error()
        if self._tables_pending is not None:
            self._tables_final = jax.block_until_ready(
                self._tables_pending)
            self._tables_pending = None
        return self._tables_final

    @staticmethod
    def _is_ckpt(item) -> bool:
        return (isinstance(item, tuple) and len(item) == 3
                and item[0] == "ckpt")

    @staticmethod
    def _serve_ckpt(item, tables) -> None:
        """Fulfil one checkpoint_sessions request against the current
        between-windows carry (its buffers are live until the next
        dispatch donates them — the copy must land before that)."""
        from vpp_tpu.pipeline.tables import SESSION_FIELDS

        _, ev, box = item
        box["sessions"] = {f: jnp.copy(getattr(tables, f))
                           for f in SESSION_FIELDS}
        ev.set()

    # --- stager: refill queue -> staged windows -> device dispatch ---
    def _stage_loop(self) -> None:
        # the window program donates its whole carry (tables + cursor),
        # so the pump must OWN the buffers it threads: copy the
        # dataplane's live tables once here — the first window's
        # donation must not invalidate arrays the collector/CLI/
        # expire_sessions still read off dp.tables (they see the
        # pre-loop state until stop() grafts sessions back, exactly
        # the r6 in-loop-carry staleness contract)
        tables = jax.tree_util.tree_map(jnp.copy, self._tables0)
        cursor = self._cursor0
        try:
            stopping = False
            while not stopping:
                item = self._in.get()
                # session checkpoints at the window boundary: served
                # against the current carry, whose buffers are valid
                # exactly here (the next dispatch donates them)
                while self._is_ckpt(item):
                    self._serve_ckpt(item, tables)
                    item = self._in.get()
                if item is None:
                    break
                # a free window, or None while the fetch side is wedged
                # — poll so a fetcher death can't deadlock the stager
                while True:
                    got = self.ring.acquire(timeout=0.2)
                    if got is not None:
                        break
                    if self._error is not None:
                        return
                widx, desc, nows, stamps = got
                n = 0
                pending_ckpt = None
                preempted = False
                # adaptive fill: drain whatever is already queued up to
                # the window size (capped by the governor's fill
                # limit), never wait for more — a lone frame ships in
                # a 1-slot window (latency floor), a backlog fills the
                # window (throughput). A PRIORITY slot ships the
                # window immediately (ISSUE 13): the reflex lane's
                # bounded queueing comes from never draining backlog
                # into a window a priority frame is already in.
                limit = min(self.ring.slots, self._fill_limit)
                while True:
                    now, stamp_us, flat, pri = item
                    desc[n] = flat
                    nows[n] = now
                    stamps[n] = stamp_us
                    n += 1
                    if pri:
                        # a preempt is a window shipped early ONLY
                        # when backlog was actually waiting to fill it
                        # — a lone priority frame on an idle queue
                        # ships the same 1-slot window either way
                        preempted = self._in.qsize() > 0
                        break
                    if n >= limit:
                        break
                    try:
                        item = self._in.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        stopping = True
                        break
                    if self._is_ckpt(item):
                        # close the window here; the request is served
                        # below against the POST-window carry (also a
                        # window boundary — still a consistent copy)
                        pending_ckpt = item
                        break
                # ONE async dispatch ships the window; the tx ring +
                # aux ride back in the fetcher's one result fetch.
                # faults: "ring.dispatch" stands in for a device
                # transfer error here — it kills this stager exactly
                # like a real dispatch failure, which is what arms the
                # pump's ring→dispatch degraded fallback
                faults.fire("ring.dispatch")
                if self._tel != "off":
                    from vpp_tpu.ops.telemetry import tel_clock_us

                    # per-packet wire latency is computed ON DEVICE at
                    # tx-append: the window ships the per-slot stamp
                    # lane + this dispatch clock, and the histogram
                    # bins ride back in the ONE result fetch below —
                    # no callback enters the program for telemetry
                    tables, cursor, tx_ring, aux_ring, tel = \
                        self._step(tables, cursor, desc, nows, stamps,
                                   np.int32(tel_clock_us()),
                                   np.int32(n))
                else:
                    tables, cursor, tx_ring, aux_ring = self._step(
                        tables, cursor, desc, nows, np.int32(n))
                    tel = None
                self.ring.note_fill(n)
                with self._stats_lock:
                    self.stats["windows_dispatched"] += 1
                    if preempted:
                        self.stats["priority_preempts"] += 1
                self._fetch_q.put((widx, n, tx_ring, aux_ring, tel))
                if pending_ckpt is not None:
                    self._serve_ckpt(pending_ckpt, tables)
            self._tables_pending = tables
        except BaseException as e:  # noqa: BLE001 — re-raised to the
            # caller from result()/stop(); a silently dead pump would
            # leave result() blocking to timeout
            self._error = e
        finally:
            if self._tables_pending is None and self._error is None:
                self._tables_pending = tables
            self._fetch_q.put(_SENTINEL)
            # unblock checkpoint requesters stranded behind the stop
            # sentinel (or a stager death): their wait would otherwise
            # run to its timeout for nothing
            while True:
                try:
                    item = self._in.get_nowait()
                except queue.Empty:
                    break
                if self._is_ckpt(item):
                    item[1].set()  # no "sessions" key = declined

    # --- fetcher: one result fetch per window, per-frame hand-off ---
    def _fetch_loop(self) -> None:
        try:
            while True:
                item = self._fetch_q.get()
                if item is _SENTINEL:
                    return
                widx, n, tx_ring, aux_ring, tel = item
                # the window's ONE device->host transfer: tx
                # descriptors + per-slot aux summaries + (telemetry
                # on) the packed telemetry rider, together
                # (faults: "ring.fetch" = the transfer failing)
                faults.fire("ring.fetch")
                if tel is not None:
                    out_h, aux_h, tel_h = jax.device_get(
                        (tx_ring, aux_ring, tel))
                    count_device_transfer("ring.window",
                                          (out_h, aux_h, tel_h))
                    with self._stats_lock:
                        self._tel_last = np.array(tel_h, np.int32)
                else:
                    out_h, aux_h = jax.device_get((tx_ring, aux_ring))
                    count_device_transfer("ring.window", (out_h, aux_h))
                out_h = np.asarray(out_h)
                aux_h = np.asarray(aux_h)
                # the staging buffer is reusable once its window's
                # exchange fully completed
                self.ring.release(widx)
                for i in range(n):
                    self._out.put((np.array(out_h[i]),
                                   np.array(aux_h[i])))
                with self._stats_lock:
                    self.stats["ring_windows"] += 1
                    self.stats["ring_frames"] += n
        except BaseException as e:  # noqa: BLE001 — surfaced via
            # _check_error exactly like a stager death
            if self._error is None:
                self._error = e
