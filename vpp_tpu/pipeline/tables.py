"""Device-resident table state + host-side table compiler.

All data-plane configuration (ACL rule tables, FIB, NAT mappings, session
table, interface attributes) lives in one immutable pytree of device
arrays, ``DataplaneTables``. A renderer commit builds a *new* pytree on
the host (numpy) and swaps it in — the functional-JAX analog of VPP's
double-buffered table swap: the jitted pipeline step simply takes the
tables as an argument, so an epoch flip is one reference assignment and
in-flight vectors keep their epoch's tables.

Reference analogs: VPP ACL-plugin rule tables, ip4 FIB, NAT44 static
mappings (external C, configured via vendored vpp-agent models — see
SURVEY.md §2.3).
"""

from __future__ import annotations

import enum
import functools
import ipaddress
import logging
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from vpp_tpu.ir.rule import ANY_PORT, ContivRule
from vpp_tpu.pipeline.vector import Disposition

log = logging.getLogger("vpp_tpu.tables")


class InterfaceType(enum.IntEnum):
    NONE = 0
    POD = 1      # pod-facing interface (VPP analog: TAP/veth+af_packet)
    UPLINK = 2   # node uplink toward other nodes / cluster edge
    HOST = 3     # host-stack interface (VPP analog: tap0 to the host)


class DataplaneConfig(NamedTuple):
    """Static sizing of the device tables (shapes are compile-time)."""

    max_tables: int = 16       # local ACL table slots
    max_rules: int = 128       # rules per local table (padded)
    max_global_rules: int = 128
    max_ifaces: int = 64
    fib_slots: int = 128
    # FIB lookup implementation (ops/fib.py dense masked-compare,
    # ops/lpm.py binary-search-over-prefix-lengths): "dense" | "lpm" |
    # "auto". ``auto`` picks LPM once the staged route count reaches
    # ``fib_lpm_min_routes`` (and the per-length planes fit
    # ``fib_lpm_mem_mb``, and every staged route fits its length's
    # plane — the BV ok-gate pattern). Re-evaluated at every epoch
    # swap; plane SHAPES are config-static, so only the selection
    # flips per epoch, never the compiled programs' signatures
    # (docs/ROUTING.md).
    fib_impl: str = "auto"
    fib_lpm_min_routes: int = 256
    fib_lpm_mem_mb: int = 256
    # Per-length plane capacities, index = prefix length /0../32
    # (missing tail entries = 0 = length unpopulated, SKIPPED at trace
    # time). Empty (the default) sizes every length to ``fib_slots`` —
    # correct for any route mix; internet-scale configs set the feed's
    # real length distribution to keep plane memory at ~8 bytes/route
    # (ops/lpm.py has the formula).
    fib_lpm_plen_caps: tuple = ()
    # ECMP next-hop groups (ops/fib.py resolve_fib_slot): group slots
    # and member ways per group (power of two — the flow-hash member
    # pick masks with W-1). 0 groups (the default) carries [1, 1]
    # placeholders and set_nh_group is refused.
    fib_ecmp_groups: int = 0
    fib_ecmp_ways: int = 8
    # Reflective-session table: total slots (power of 2), organized as
    # sess_slots/sess_ways buckets of sess_ways ways each (W-way
    # set-associative — ops/session.py). Memory is ~6 uint32 columns x
    # sess_slots (24 B/slot): 1<<24 slots ≈ 402 MB serves 10M+
    # concurrent sessions at ~0.6 load factor (docs/SESSIONS.md).
    sess_slots: int = 4096
    # Ways per bucket (power of 2, divides sess_slots). 4 is the VPP/
    # CPU-cache sweet spot: one bucket row gather fetches the whole
    # associativity set.
    sess_ways: int = 4
    # Session probe implementation: "gather" (the proven row-gather
    # rung), "pallas" (the fused probe kernel, ISSUE 16 — requires a
    # TPU backend and the table to fit the kernel's VMEM budget,
    # ops/session.session_pallas_fits; falls back to gather when
    # ineligible), or "auto" (pallas when eligible). Standalone only:
    # a mesh with an explicit pallas knob is rejected at config time
    # (parallel/partition.py validate_partitioning).
    session_impl: str = "auto"
    # Session bucket hash family (ops/session.py): "fwd" hashes the
    # forward 5-tuple (the classic single-instance layout); "sym"
    # canonicalizes the tuple (address-pair ordered) so BOTH directions
    # of a flow land in the same bucket without knowing direction —
    # required by the fleet steering tier (vpp_tpu/fleet/,
    # docs/FLEET.md), which maps packets to instances by session
    # bucket range from OUTSIDE the dataplane. Only bucket placement
    # changes; stored keys, key comparison and hit semantics are
    # identical. Trace-time static (part of the step-factory key).
    sess_hash: str = "fwd"
    # NAT-session table slots; 0 = same as sess_slots (shares sess_ways)
    natsess_slots: int = 0
    # Amortized on-device aging: every fused pipeline step sweeps this
    # many buckets per table (idle-expired entries are invalidated and
    # the cursor advances; a full cycle takes n_buckets/stride steps).
    # 0 disables the in-step sweep (bulk expire_sessions only).
    sess_sweep_stride: int = 256
    # Session/NAT idle timeout in clock ticks (Dataplane.TICKS_PER_SEC =
    # 10/s, so 3000 = 300 s — VPP's default TCP established timeout
    # order). Enforced in-kernel: lookups ignore expired entries and
    # inserts reclaim their slots, so timeout precision doesn't depend
    # on the host aging loop's cadence.
    sess_max_age: int = 3000
    nat_mappings: int = 64     # DNAT static mapping slots
    nat_backends: int = 512    # total backend slots across mappings
    # Two-tier established-flow fast path (pipeline/graph.py
    # pipeline_step_auto): batches where every valid packet hits a live
    # reflective session dispatch to a classify-free kernel. ``fastpath``
    # is the master switch; ``fastpath_min_rules`` gates engagement on
    # the global table size (below it the classifier is cheap enough
    # that the dispatch predicate buys nothing — the mxu_threshold
    # analog). Both kernels (and their MXU variants) are compiled and
    # cached per epoch by the Dataplane exactly like the full chain.
    fastpath: bool = True
    fastpath_min_rules: int = 0
    # Global-classify implementation (ops/acl.py dense VPU compare,
    # ops/acl_mxu.py bit-plane matmul, ops/acl_bv.py interval-bitmap
    # bit-vector): "dense" | "mxu" | "bv" | "auto". ``auto`` picks BV
    # once the global table reaches ``classifier_bv_min_rules`` (and
    # the worst-case interval-bitmap structure fits
    # ``classifier_bv_mem_mb`` — ~5 x 2R x R/32 uint32 words, ~105 MB
    # at 10,240 rules), the MXU kernel above Dataplane.mxu_threshold,
    # dense below. Re-evaluated at every epoch swap against the staged
    # rule count; the structure's SHAPES are config-static, so only
    # the selection flips per epoch, never the compiled programs'
    # signatures. BV also serves the per-interface local tables (MXU
    # is global-only); the multi-chip mesh keeps its rule-sharded
    # dense/MXU classify (docs/CLASSIFIER.md).
    classifier: str = "auto"
    classifier_bv_min_rules: int = 1024
    classifier_bv_mem_mb: int = 256
    # Per-packet ML scoring stage (ops/mlscore.py; docs/ML_STAGE.md):
    # "off" elides the stage from the compiled step entirely (and the
    # glb_ml_* fields carry minimal placeholder shapes, the BV
    # allocation-gating pattern); "score" computes + counts + exports
    # verdicts only; "enforce" additionally folds the model's
    # drop/ratelimit decisions into the pipeline verdict (ordered
    # deny > ml-drop > permit). The staged MODEL arrives through
    # TableBuilder.set_ml_model (epoch-swapped like ACL rules); with
    # no model staged the stage stays compiled-out even when the knob
    # says score/enforce (re-gated at every swap, the fastpath
    # pattern).
    ml_stage: str = "off"
    # capacity ceilings of the staged model (compile-time SHAPES; a
    # smaller model zero-pads, a larger one is refused at staging)
    ml_hidden: int = 16        # MLP hidden width
    ml_trees: int = 4          # oblivious-forest tree count
    ml_depth: int = 3          # oblivious-forest depth (leaves = 2^D)
    # Device-resident telemetry plane (ops/telemetry.py; ISSUE 11):
    # "off" compiles the stage out entirely and carries minimal
    # placeholder shapes (the ml_stage pattern — the off-state programs
    # are byte-identical to pre-telemetry); "latency" enables the
    # in-step wire-latency log2 histogram; "full" adds the count-min
    # heavy-hitter flow sketch + top-K candidate table. The planes ride
    # this pytree like the sweep cursors (epoch swaps carry them by
    # reference; the persistent ring threads them window-to-window).
    telemetry: str = "off"
    telemetry_lat_buckets: int = 24   # log2 µs bins (last saturates)
    telemetry_sketch_rows: int = 2    # count-min depth d
    telemetry_sketch_cols: int = 1024  # count-min width w (power of 2)
    telemetry_topk: int = 8           # heavy-hitter candidate slots
    # Multi-tenant gateway mode (ISSUE 14; vpp_tpu/tenancy/,
    # docs/TENANCY.md): "off" compiles the tenant stage out entirely
    # and the tnt_* fields carry minimal placeholder shapes (the
    # telemetry/ml gating pattern); "on" derives a per-packet tenant
    # id at ip4-input from the src/dst prefix map (its own "tenant"
    # upload group), runs the per-tenant token-bucket rate limit
    # inside the fused step (overage → DROP_TENANT, attributed
    # drops_total{reason="tenant_quota"}), slices session/NAT bucket
    # capacity per tenant (TableBuilder.set_tenant sess_buckets — a
    # full slice fails/evicts only WITHIN the owning tenant, never
    # across), and keys the ML flag threshold/mode by tenant.
    tenancy: str = "off"
    tenancy_tenants: int = 8          # tenant-id capacity (1..64)
    tenancy_prefixes: int = 64        # prefix-map slots
    # Device-resident VXLAN overlay (ops/vxlan.py; ISSUE 19;
    # docs/OVERLAY.md): "off" compiles the stage pair out entirely —
    # the step programs are byte-identical to pre-overlay; "vxlan"
    # decaps VTEP-addressed UDP/4789 frames at ip4-input (outer header
    # + VNI validated on-device, the inner vector re-admitted in
    # place, VNI → tenant handed to the tenancy derivation) and
    # builds the per-destination-node outer header at tx (entropy
    # sport from the inner 5-tuple, outer endpoint resolved by a
    # SECOND walk over the same FIB planes — LPM/ECMP carry over
    # unchanged). ONE new step-form dimension in the process-wide jit
    # cache; zero io_callbacks on the wire path.
    overlay: str = "off"
    # Service NAT44 LB planes (ops/nat44.py svc path; ISSUE 19): VIP
    # row capacity of the svc_* tables. 0 (default) carries [1, B]
    # placeholders with bk_n 0 — rows that can never serve — and
    # set_service is refused; the svc consult then costs one gather
    # against a 1-row table. The planes ride their OWN "svc" upload
    # group, so rolling backend churn ships a few-KB blob and ZERO
    # ACL/ML/FIB bytes.
    svc_vips: int = 0
    # Backend ways per VIP row (power of two — the flow-hash backend
    # pick masks with B-1). Way assignment is STICKY across backend
    # churn (the set_nh_group fill), so a rolling replacement only
    # remaps the ways it must.
    svc_backend_ways: int = 8


class DataplaneTables(NamedTuple):
    """The device table pytree. All arrays live in HBM; see module doc."""

    # --- ACL local tables, padded [T, R] ---
    acl_src_net: jnp.ndarray    # uint32, pre-masked network address
    acl_src_mask: jnp.ndarray   # uint32
    acl_dst_net: jnp.ndarray    # uint32
    acl_dst_mask: jnp.ndarray   # uint32
    acl_proto: jnp.ndarray      # int32 IANA proto, -1 = any, -2 = padding
    acl_sport_lo: jnp.ndarray   # int32 (padding rows: lo=1, hi=0)
    acl_sport_hi: jnp.ndarray   # int32
    acl_dport_lo: jnp.ndarray   # int32
    acl_dport_hi: jnp.ndarray   # int32
    acl_action: jnp.ndarray     # int32: 0 deny, 1 permit, -1 padding
    acl_nrules: jnp.ndarray     # int32 [T]
    # Interval-bitmap (BV) form of the local tables (ops/acl_bv.py);
    # minimal placeholder shapes when the classifier knob disables BV
    # (bv_capacity(enabled=False)) — shapes stay epoch-invariant.
    acl_bv_bnd_src: jnp.ndarray    # uint32 [T, I]
    acl_bv_bnd_dst: jnp.ndarray    # uint32 [T, I]
    acl_bv_bnd_sport: jnp.ndarray  # int32 [T, I]
    acl_bv_bnd_dport: jnp.ndarray  # int32 [T, I]
    acl_bv_nbnd: jnp.ndarray       # int32 [T, 4] live boundary counts
    acl_bv_src: jnp.ndarray        # uint32 [T, I, W] segment bitmaps
    acl_bv_dst: jnp.ndarray        # uint32 [T, I, W]
    acl_bv_sport: jnp.ndarray      # uint32 [T, I, W]
    acl_bv_dport: jnp.ndarray      # uint32 [T, I, W]
    acl_bv_proto: jnp.ndarray      # uint32 [T, PR, W] direct proto plane

    # --- global ACL table, padded [G] ---
    glb_src_net: jnp.ndarray
    glb_src_mask: jnp.ndarray
    glb_dst_net: jnp.ndarray
    glb_dst_mask: jnp.ndarray
    glb_proto: jnp.ndarray
    glb_sport_lo: jnp.ndarray
    glb_sport_hi: jnp.ndarray
    glb_dport_lo: jnp.ndarray
    glb_dport_hi: jnp.ndarray
    glb_action: jnp.ndarray
    glb_nrules: jnp.ndarray     # int32 scalar
    # Bit-plane form of the global table for the MXU classify kernel
    # (vpp_tpu.ops.acl_mxu); float32 {-1,0,1} coeffs, cast to bf16 at use.
    glb_mxu_coeff: jnp.ndarray  # float32 [PLANES, R']
    glb_mxu_k: jnp.ndarray      # float32 [R']
    glb_mxu_act: jnp.ndarray    # int32 [R'] action per bit-plane COLUMN
                                # (-1 padding) — column space can be wider
                                # than rule-row space (R' >= R), so the
                                # rule-sharded MXU classify must resolve
                                # the deny bit here, not via glb_action
    # Interval-bitmap (BV) form of the global table (ops/acl_bv.py);
    # its own upload group ("glb_bv"), re-uploaded per-dimension-plane
    # so a port-only policy churn doesn't re-ship the address bitmaps.
    # On the mesh the bitmap planes shard along the rule-WORD axis
    # (boundaries replicated — a segment's row spans ALL rules, but
    # packs them into words): vpp_tpu/parallel/partition.py,
    # docs/CLASSIFIER.md.
    glb_bv_bnd_src: jnp.ndarray    # uint32 [I]
    glb_bv_bnd_dst: jnp.ndarray    # uint32 [I]
    glb_bv_bnd_sport: jnp.ndarray  # int32 [I]
    glb_bv_bnd_dport: jnp.ndarray  # int32 [I]
    glb_bv_nbnd: jnp.ndarray       # int32 [4]
    glb_bv_src: jnp.ndarray        # uint32 [I, W]
    glb_bv_dst: jnp.ndarray        # uint32 [I, W]
    glb_bv_sport: jnp.ndarray      # uint32 [I, W]
    glb_bv_dport: jnp.ndarray      # uint32 [I, W]
    glb_bv_proto: jnp.ndarray      # uint32 [PR, W]

    # --- per-packet ML model (ops/mlscore.py; upload group "ml") ---
    # Shipped through set_ml_model exactly like ACL rules ship through
    # set_global_table: its OWN upload group, so policy churn never
    # re-ships the model and a model swap never re-ships the rules.
    # Minimal placeholder shapes when ml_stage is "off"
    # (ml_capacity(config)); biases are zero-point FOLDED (int8
    # features are centered x-128 — _fold_ml below).
    glb_ml_w1: jnp.ndarray       # int8 [F, H] layer-1 weights
    glb_ml_b1: jnp.ndarray       # int32 [H] layer-1 bias (folded)
    glb_ml_s1: jnp.ndarray       # int32 scalar: requant right shift
    glb_ml_w2: jnp.ndarray       # int8 [H] output weights
    glb_ml_b2: jnp.ndarray       # int32 scalar: output bias (folded)
    glb_ml_f_feat: jnp.ndarray   # int32 [T, D] forest feature index
    glb_ml_f_thresh: jnp.ndarray  # int32 [T, D] forest thresholds
    glb_ml_f_leaf: jnp.ndarray   # int32 [T, 2^D] forest leaf votes
    glb_ml_thresh: jnp.ndarray   # int32 scalar: score > t => flagged
    glb_ml_action: jnp.ndarray   # int32 scalar: ML_ACTION_* policy
    glb_ml_rl_shift: jnp.ndarray  # int32 scalar: ratelimit admit shift
    glb_ml_version: jnp.ndarray  # int32 scalar: staged model version

    # --- interfaces [I] ---
    if_type: jnp.ndarray        # int32 InterfaceType
    if_local_table: jnp.ndarray  # int32 local ACL table slot, -1 = none
    if_apply_global: jnp.ndarray  # int32 bool: global table applies here

    # --- FIB [F] ---
    fib_prefix: jnp.ndarray     # uint32 pre-masked
    fib_mask: jnp.ndarray       # uint32
    fib_plen: jnp.ndarray       # int32, -1 = empty slot
    fib_tx_if: jnp.ndarray      # int32
    fib_disp: jnp.ndarray       # int32 Disposition
    fib_next_hop: jnp.ndarray   # uint32 (peer/VXLAN dst IP, else 0)
    fib_node_id: jnp.ndarray    # int32 remote node index (ICI), -1 local
    fib_snat: jnp.ndarray       # int32 bool: cluster-egress route — SNAT
                                # applies (reference: configurator_impl.go
                                # :258-264 SNAT pool for external traffic)
    fib_grp: jnp.ndarray        # int32 [F] ECMP next-hop group of the
                                # route, -1 = unicast (the scalar
                                # next_hop/tx_if/node_id columns above)

    # --- LPM per-length prefix planes (ops/lpm.py; ISSUE 15) --------
    # One [2, N_L] uint32 plane per prefix length: row 0 the sorted
    # masked prefixes (pad 0xFFFFFFFF), row 1 the owning FIB slot.
    # SEPARATE fields deliberately — a BGP flap re-ships only the
    # touched length's plane; the others keep device-array identity
    # (the glb_bv per-dimension-plane discipline). Capacities are
    # config-static (fib_lpm_plen_caps; 0 = zero-width plane, skipped
    # at trace time). Replicated along the mesh rule axis
    # (parallel/partition.py).
    fib_lpm_p0: jnp.ndarray
    fib_lpm_p1: jnp.ndarray
    fib_lpm_p2: jnp.ndarray
    fib_lpm_p3: jnp.ndarray
    fib_lpm_p4: jnp.ndarray
    fib_lpm_p5: jnp.ndarray
    fib_lpm_p6: jnp.ndarray
    fib_lpm_p7: jnp.ndarray
    fib_lpm_p8: jnp.ndarray
    fib_lpm_p9: jnp.ndarray
    fib_lpm_p10: jnp.ndarray
    fib_lpm_p11: jnp.ndarray
    fib_lpm_p12: jnp.ndarray
    fib_lpm_p13: jnp.ndarray
    fib_lpm_p14: jnp.ndarray
    fib_lpm_p15: jnp.ndarray
    fib_lpm_p16: jnp.ndarray
    fib_lpm_p17: jnp.ndarray
    fib_lpm_p18: jnp.ndarray
    fib_lpm_p19: jnp.ndarray
    fib_lpm_p20: jnp.ndarray
    fib_lpm_p21: jnp.ndarray
    fib_lpm_p22: jnp.ndarray
    fib_lpm_p23: jnp.ndarray
    fib_lpm_p24: jnp.ndarray
    fib_lpm_p25: jnp.ndarray
    fib_lpm_p26: jnp.ndarray
    fib_lpm_p27: jnp.ndarray
    fib_lpm_p28: jnp.ndarray
    fib_lpm_p29: jnp.ndarray
    fib_lpm_p30: jnp.ndarray
    fib_lpm_p31: jnp.ndarray
    fib_lpm_p32: jnp.ndarray
    fib_lpm_cnt: jnp.ndarray    # int32 [33] live (deduped) entries per
                                # length plane, clipped to each cap
    fib_lpm_hint: jnp.ndarray   # int32 [H] concatenated per-length
                                # stride hint tables (ops/lpm.py
                                # lpm_hint_layout — offsets are
                                # config-static, derived from the caps)

    # --- ECMP next-hop group tables (ops/fib.py; ISSUE 15) ----------
    # [G, W] member tables, member picked by the session flow hash
    # (way = mix & (W-1)); fib_grp_n counts DISTINCT members (0 =
    # unconfigured group — routes referencing it fail closed).
    fib_grp_nh: jnp.ndarray     # uint32 [G, W] member next-hop IP
    fib_grp_tx_if: jnp.ndarray  # int32 [G, W]
    fib_grp_node: jnp.ndarray   # int32 [G, W]
    fib_grp_n: jnp.ndarray      # int32 [G] distinct member count
    # per-member forwarded-packet accounting (graph._finish_step
    # scatter-add; the vpp_tpu_fib_ecmp_packets family) — STATE,
    # carried by reference across swaps like the telemetry planes
    fib_ecmp_c: jnp.ndarray     # int32 [G, W]

    # --- reflective sessions (W-way set-associative hash) [NB, W] ---
    # The way count W is carried IN THE SHAPE (ops/session.py): one
    # bucket-row gather fetches a flow's whole associativity set.
    sess_src: jnp.ndarray       # uint32 [NB, W]
    sess_dst: jnp.ndarray       # uint32 [NB, W]
    sess_ports: jnp.ndarray     # uint32 [NB, W] (sport<<16 | dport)
    sess_proto: jnp.ndarray     # int32 [NB, W]
    sess_valid: jnp.ndarray     # int32 bool [NB, W]
    sess_time: jnp.ndarray      # int32 [NB, W] last-hit tick (aging)
    sess_max_age: jnp.ndarray   # int32 scalar: idle timeout in ticks

    # --- NAT44 DNAT mappings [M] + backends [B] ---
    nat_ext_ip: jnp.ndarray     # uint32 service VIP / node IP
    nat_ext_port: jnp.ndarray   # int32
    nat_proto: jnp.ndarray      # int32
    nat_boff: jnp.ndarray       # int32 offset into backend arrays
    nat_bcnt: jnp.ndarray       # int32 backend count (0 = empty slot)
    nat_total_w: jnp.ndarray    # int32 total backend weight
    nat_self_snat: jnp.ndarray  # int32 bool [M]: DNAT'd flows of this
                                # mapping are also SNAT'd (nodeport case:
                                # the reply must return via this node)
    natb_ip: jnp.ndarray        # uint32 [B]
    natb_port: jnp.ndarray      # int32 [B]
    natb_cumw: jnp.ndarray      # int32 [B] cumulative weight within mapping
    nat_snat_ip: jnp.ndarray    # uint32 scalar: SNAT address (node IP)

    # --- NAT44 session table (reverse translation state) [NNB, W] ---
    # key: the flow as the *reply* will present it,
    # (reply_src_ip, reply_dst_ip, reply_sport<<16|reply_dport, proto)
    natsess_a: jnp.ndarray          # uint32
    natsess_b: jnp.ndarray          # uint32
    natsess_ports: jnp.ndarray      # uint32
    natsess_proto: jnp.ndarray      # int32
    natsess_valid: jnp.ndarray      # int32
    natsess_time: jnp.ndarray       # int32
    natsess_orig_ip: jnp.ndarray    # uint32 original dst (service VIP)
    natsess_orig_port: jnp.ndarray  # int32 original dst port
    natsess_src_ip: jnp.ndarray     # uint32 original src (pre-SNAT pod IP)
    natsess_sport: jnp.ndarray      # int32 original src port
    natsess_kind: jnp.ndarray       # int32 bitmask: 1=DNAT'd, 2=SNAT'd

    # --- amortized aging cursors (ops/session.py session_sweep) ---
    # next bucket each in-step sweep starts from; int32 scalars that
    # ride the session-state carry-over so a swap never resets aging
    sess_sweep_cursor: jnp.ndarray
    natsess_sweep_cursor: jnp.ndarray

    # --- device-resident telemetry plane (ops/telemetry.py; ISSUE 11) --
    # Carried across epoch swaps by reference like the session state
    # (TELEMETRY_FIELDS below); minimal placeholder shapes when the
    # ``telemetry`` knob is off (tel_capacity — the ml/BV gating
    # pattern, the placeholders are never read by an off-state step).
    tel_lat_hist: jnp.ndarray   # int32 [NB] log2 µs wire-latency bins
    tel_sketch: jnp.ndarray    # int32 [d, w] count-min flow sketch
    tel_sketched: jnp.ndarray  # int32 scalar: packets folded in
    tel_top_key: jnp.ndarray   # uint32 [K] top-K candidate flow hash
    tel_top_src: jnp.ndarray   # uint32 [K] candidate src ip
    tel_top_dst: jnp.ndarray   # uint32 [K] candidate dst ip
    tel_top_ports: jnp.ndarray  # uint32 [K] sport<<16 | dport
    tel_top_cnt: jnp.ndarray   # int32 [K] estimated packet count

    # --- multi-tenant gateway mode (ISSUE 14; vpp_tpu/tenancy/) -----
    # Config half ("tenant" upload group — ships independently of
    # rules/model, so tenant churn re-ships a few hundred bytes and
    # rule/model churn re-ships zero tenant state). Placeholder [1]
    # shapes when the ``tenancy`` knob is off (tnt_capacity).
    tnt_pfx_net: jnp.ndarray    # uint32 [S] pre-masked prefix network
    tnt_pfx_mask: jnp.ndarray   # uint32 [S]
    tnt_pfx_id: jnp.ndarray     # int32 [S] tenant id, -1 = empty slot
    tnt_rate: jnp.ndarray       # int32 [T] bucket tokens/tick (0 = no
                                # limit; bounded 2^16 — int32 refill)
    tnt_burst: jnp.ndarray      # int32 [T] bucket capacity
    tnt_sess_base: jnp.ndarray  # int32 [T] first session bucket of the
                                # tenant's slice (GLOBAL bucket units)
    tnt_sess_mask: jnp.ndarray  # int32 [T] slice bucket mask (nbk-1;
                                # unsliced tenants carry the full-table
                                # mask — base 0)
    tnt_nat_base: jnp.ndarray   # int32 [T] NAT-session slice base
    tnt_nat_mask: jnp.ndarray   # int32 [T] NAT-session slice mask
    # per-tenant ML policy vectors (tenancy/sched.py ML_MODE_CODES:
    # 0 inherit | 1 off | 2 score | 3 enforce; thresh INT32_MIN =
    # inherit the model's global flag threshold). Deliberately in the
    # "tenant" group, NOT "ml": flipping a tenant's threshold/mode
    # never re-ships the weight planes (ISSUE 14 satellite).
    glb_ml_tnt_mode: jnp.ndarray    # int32 [T]
    glb_ml_tnt_thresh: jnp.ndarray  # int32 [T]
    # Direct VNI → tenant map (ISSUE 19 satellite: the overlay decap
    # stage derives the tenant from the VALIDATED VNI on-device, so
    # tunneled traffic no longer depends on inner-address prefixes).
    # tnt_vni[t] is tenant t's VNI (-1 = none); a decapped VNI that
    # maps to no tenant FAILS CLOSED (DROP_OVERLAY). Tenancy-off
    # placeholder [1] carries DEFAULT_VNI so the single-tenant overlay
    # admits VNI 10 and nothing else.
    tnt_vni: jnp.ndarray        # int32 [T]
    # State half (TENANCY_STATE_FIELDS — carried by reference across
    # swaps like the sweep cursors; the persistent ring threads them
    # window-to-window): token-bucket level + last-refill tick, and
    # the per-tenant accounting planes `show tenants` /
    # vpp_tpu_tenant_* read as host scalars.
    tnt_tokens: jnp.ndarray     # int32 [T] current bucket level
    tnt_tok_time: jnp.ndarray   # int32 [T] last refill tick
    tnt_rx_c: jnp.ndarray       # int32 [T] packets received
    tnt_tx_c: jnp.ndarray       # int32 [T] packets forwarded (goodput)
    tnt_rl_c: jnp.ndarray       # int32 [T] rate-limit (tenant_quota)
                                # drops
    tnt_qf_c: jnp.ndarray       # int32 [T] session-slice insert
                                # failures attributed to the tenant

    # --- VXLAN overlay config (ops/vxlan.py; ISSUE 19) --------------
    # The node's local VTEP address; rides the tiny "config" upload
    # group (one scalar — a VTEP move ships bytes, not planes). 0 =
    # unset: decap then admits any VTEP-addressed UDP/4789 frame (the
    # single-node test harness), encap still stamps it as outer src.
    ovl_vtep_ip: jnp.ndarray    # uint32 scalar

    # --- service NAT44 LB planes (ops/nat44.py svc path; ISSUE 19) --
    # VIP rows sorted by (ip, port, proto) — the --tables invariant —
    # with padding rows inert via svc_bk_n == 0 (a row with no staged
    # backend set must NEVER serve: the half-applied-churn guard).
    # Backend columns are WAY tables, member picked by the session
    # flow hash (way = mix & (B-1)) with sticky weighted fill
    # (set_service — the set_nh_group discipline), so backend churn
    # only remaps the ways it must. Their OWN "svc" upload group: a
    # rolling backend replacement ships a few-KB scatter blob and
    # zero ACL/ML/FIB bytes (_upload_svc).
    svc_vip_ip: jnp.ndarray     # uint32 [V] service VIP
    svc_vip_port: jnp.ndarray   # int32 [V] service port (exact match)
    svc_vip_proto: jnp.ndarray  # int32 [V] IANA proto
    svc_vip_snat: jnp.ndarray   # int32 bool [V]: nodeport-style —
                                # DNAT'd flows also SNAT (reply must
                                # return via this node)
    svc_bk_n: jnp.ndarray       # int32 [V] distinct backends (0 =
                                # empty/padding row, never serves)
    svc_bk_ip: jnp.ndarray      # uint32 [V, B] per-way backend IP
    svc_bk_port: jnp.ndarray    # int32 [V, B] per-way backend port


def _mask_of(plen: int, bits: int = 32) -> int:
    return ((1 << bits) - 1) ^ ((1 << (bits - plen)) - 1) if plen else 0


# Session-state fields of DataplaneTables (reflective ACL + NAT session
# tables + sweep cursors) with their dtypes — the single source for
# zero-initialization and for epoch-swap carry-over. The shape KIND of
# each field lives in _SESSION_SHAPE: "sess"/"natsess" are [NB, W]
# bucket grids, "scalar" is the per-table sweep cursor.
SESSION_FIELDS: Dict[str, type] = {
    "sess_src": np.uint32, "sess_dst": np.uint32, "sess_ports": np.uint32,
    "sess_proto": np.int32, "sess_valid": np.int32, "sess_time": np.int32,
    "natsess_a": np.uint32, "natsess_b": np.uint32, "natsess_ports": np.uint32,
    "natsess_proto": np.int32, "natsess_valid": np.int32,
    "natsess_time": np.int32, "natsess_orig_ip": np.uint32,
    "natsess_orig_port": np.int32, "natsess_src_ip": np.uint32,
    "natsess_sport": np.int32, "natsess_kind": np.int32,
    "sess_sweep_cursor": np.int32, "natsess_sweep_cursor": np.int32,
}

_SESSION_SHAPE: Dict[str, str] = {
    k: ("scalar" if k.endswith("_sweep_cursor")
        else "natsess" if k.startswith("natsess_") else "sess")
    for k in SESSION_FIELDS
}


def natsess_slots_of(config: DataplaneConfig) -> int:
    """Effective NAT-session slot count (the knob's 0 default means
    'same as sess_slots')."""
    n = int(getattr(config, "natsess_slots", 0) or 0)
    return n if n else config.sess_slots


def session_shapes(config: DataplaneConfig) -> Dict[str, Tuple[int, ...]]:
    """Per-field session-state shapes (no leading axes): the bucket
    grid [slots/ways, ways] per table, () for the sweep cursors."""
    w = int(getattr(config, "sess_ways", 4))
    shapes = {
        "sess": (config.sess_slots // w, w),
        "natsess": (natsess_slots_of(config) // w, w),
        "scalar": (),
    }
    return {k: shapes[_SESSION_SHAPE[k]] for k in SESSION_FIELDS}


def zero_sessions(config: DataplaneConfig, leading: Tuple[int, ...] = ()) -> Dict[str, np.ndarray]:
    """Fresh (empty) session-state arrays, optionally with leading axes
    (the cluster data plane stacks per-node session tables)."""
    shapes = session_shapes(config)
    return {k: np.zeros(leading + shapes[k], dt)
            for k, dt in SESSION_FIELDS.items()}


def zero_sessions_device(config: DataplaneConfig) -> Dict[str, jnp.ndarray]:
    """Device-resident fresh session state: ``jnp.zeros`` fills on the
    accelerator instead of shipping host zero buffers — at the 10M-slot
    regime the session columns are hundreds of MB, and uploading zeros
    over a remote transport (the axon tunnel) is pure waste."""
    shapes = session_shapes(config)
    return {k: jnp.zeros(shapes[k], dt)
            for k, dt in SESSION_FIELDS.items()}


# Telemetry-plane fields of DataplaneTables (ops/telemetry.py; ISSUE
# 11) with their dtypes — the single source for zero-fill, the
# epoch-swap carry-over (to_device) and the persistent-pump stop-merge.
# Deliberately NOT part of SESSION_FIELDS: the crash-consistent
# snapshot format (pipeline/snapshot.py) enumerates SESSION_FIELDS, and
# telemetry is measurement state that restarts cold by design.
TELEMETRY_FIELDS: Dict[str, type] = {
    "tel_lat_hist": np.int32,
    "tel_sketch": np.int32,
    "tel_sketched": np.int32,
    "tel_top_key": np.uint32,
    "tel_top_src": np.uint32,
    "tel_top_dst": np.uint32,
    "tel_top_ports": np.uint32,
    "tel_top_cnt": np.int32,
}

_TELEMETRY_SHAPE: Dict[str, str] = {
    "tel_lat_hist": "lat", "tel_sketch": "sketch",
    "tel_sketched": "scalar", "tel_top_key": "topk",
    "tel_top_src": "topk", "tel_top_dst": "topk",
    "tel_top_ports": "topk", "tel_top_cnt": "topk",
}


def tel_capacity(config: DataplaneConfig) -> Tuple[int, int, int, int]:
    """(lat_buckets, sketch_rows, sketch_cols, topk) of the telemetry
    planes. "off" carries minimal placeholders (never read — the step
    factory compiles the stage out); "latency" keeps the sketch/top-K
    planes at placeholder size too."""
    mode = getattr(config, "telemetry", "off")
    if mode == "off":
        return 1, 1, 1, 1
    nb = int(getattr(config, "telemetry_lat_buckets", 24))
    if mode == "latency":
        return nb, 1, 1, 1
    return (nb, int(getattr(config, "telemetry_sketch_rows", 2)),
            int(getattr(config, "telemetry_sketch_cols", 1024)),
            int(getattr(config, "telemetry_topk", 8)))


def telemetry_shapes(config: DataplaneConfig) -> Dict[str, Tuple[int, ...]]:
    """Per-field telemetry-plane shapes (no leading axes)."""
    nb, d, w, k = tel_capacity(config)
    shapes = {"lat": (nb,), "sketch": (d, w), "topk": (k,),
              "scalar": ()}
    return {f: shapes[_TELEMETRY_SHAPE[f]] for f in TELEMETRY_FIELDS}


def zero_telemetry(config: DataplaneConfig,
                   leading: Tuple[int, ...] = ()) -> Dict[str, np.ndarray]:
    """Fresh (empty) telemetry planes, optionally node-stacked (the
    cluster data plane's leading axis, mirroring zero_sessions)."""
    shapes = telemetry_shapes(config)
    return {f: np.zeros(leading + shapes[f], dt)
            for f, dt in TELEMETRY_FIELDS.items()}


def zero_telemetry_device(config: DataplaneConfig) -> Dict[str, jnp.ndarray]:
    """Device-resident fresh telemetry planes (zero_sessions_device
    twin — the planes are small, but the fill still belongs on device)."""
    shapes = telemetry_shapes(config)
    return {f: jnp.zeros(shapes[f], dt)
            for f, dt in TELEMETRY_FIELDS.items()}


# --- multi-tenant gateway mode (ISSUE 14; vpp_tpu/tenancy/) ----------

# glb_ml_tnt_thresh sentinel: "inherit the model's global flag
# threshold" (a real threshold of -2^31 would flag every packet — not
# a usable configuration, so the sentinel costs nothing).
ML_TNT_THRESH_INHERIT = -(1 << 31)

# Tenancy STATE fields of DataplaneTables (token buckets + accounting
# planes) — carried by reference across epoch swaps and grafted back
# from the persistent ring at stop/sync, exactly like TELEMETRY_FIELDS.
# Deliberately NOT in SESSION_FIELDS: the crash-consistent snapshot
# format enumerates SESSION_FIELDS, and bucket levels/counters are
# measurement state that restarts cold by design.
TENANCY_STATE_FIELDS: Dict[str, type] = {
    "tnt_tokens": np.int32,
    "tnt_tok_time": np.int32,
    "tnt_rx_c": np.int32,
    "tnt_tx_c": np.int32,
    "tnt_rl_c": np.int32,
    "tnt_qf_c": np.int32,
}


def tnt_capacity(config: DataplaneConfig) -> Tuple[int, int]:
    """(tenants T, prefix slots S) of the tenant planes. "off" carries
    minimal placeholders (never read — the step factory compiles the
    tenant stage out, the ml/telemetry gating pattern)."""
    if getattr(config, "tenancy", "off") == "off":
        return 1, 1
    return (int(getattr(config, "tenancy_tenants", 8)),
            int(getattr(config, "tenancy_prefixes", 64)))


def tenancy_state_shapes(config: DataplaneConfig) -> Dict[str, Tuple[int, ...]]:
    t, _s = tnt_capacity(config)
    return {f: (t,) for f in TENANCY_STATE_FIELDS}


def zero_tenancy_state(config: DataplaneConfig,
                       leading: Tuple[int, ...] = ()) -> Dict[str, np.ndarray]:
    shapes = tenancy_state_shapes(config)
    return {f: np.zeros(leading + shapes[f], dt)
            for f, dt in TENANCY_STATE_FIELDS.items()}


def zero_tenancy_state_device(config: DataplaneConfig) -> Dict[str, jnp.ndarray]:
    shapes = tenancy_state_shapes(config)
    return {f: jnp.zeros(shapes[f], dt)
            for f, dt in TENANCY_STATE_FIELDS.items()}


# FIB STATE fields of DataplaneTables (the per-member ECMP accounting
# plane — ISSUE 15), carried by reference across epoch swaps exactly
# like TELEMETRY_FIELDS, cold on snapshot restore by design (the
# crash-consistent snapshot format enumerates SESSION_FIELDS only).
FIB_STATE_FIELDS: Dict[str, type] = {
    "fib_ecmp_c": np.int32,
}


def fib_state_shapes(config: DataplaneConfig) -> Dict[str, Tuple[int, ...]]:
    from vpp_tpu.ops.lpm import ecmp_capacity

    g, w = ecmp_capacity(config)
    return {f: (g, w) for f in FIB_STATE_FIELDS}


def zero_fib_state(config: DataplaneConfig,
                   leading: Tuple[int, ...] = ()) -> Dict[str, np.ndarray]:
    shapes = fib_state_shapes(config)
    return {f: np.zeros(leading + shapes[f], dt)
            for f, dt in FIB_STATE_FIELDS.items()}


def zero_fib_state_device(config: DataplaneConfig) -> Dict[str, jnp.ndarray]:
    shapes = fib_state_shapes(config)
    return {f: jnp.zeros(shapes[f], dt)
            for f, dt in FIB_STATE_FIELDS.items()}


def svc_capacity(config: DataplaneConfig) -> Tuple[int, int]:
    """(VIP rows V, backend ways B) of the service LB planes (ISSUE
    19). svc_vips 0 carries a [1, B] placeholder whose single row has
    bk_n 0 — it can never match, so the always-compiled svc consult is
    one inert gather (no step-form dimension for the svc path)."""
    b = int(getattr(config, "svc_backend_ways", 8))
    v = int(getattr(config, "svc_vips", 0))
    return (v if v > 0 else 1), b


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def validate_dataplane_config(config: DataplaneConfig) -> None:
    """Fail FAST (and intelligibly) on session-table misconfiguration.
    The hash kernels mask with ``& (n_buckets - 1)`` and the sweep
    relies on power-of-two divisibility, so a bad knob that once
    surfaced as a shape error deep inside a jit trace is rejected at
    config load instead. Called from TableBuilder (every dataplane) and
    cmd/config.py (YAML load)."""
    c = config
    ways = int(getattr(c, "sess_ways", 4))
    stride = int(getattr(c, "sess_sweep_stride", 256))
    if not _is_pow2(c.sess_slots):
        raise ValueError(
            f"dataplane.sess_slots must be a power of two, got "
            f"{c.sess_slots}")
    if not _is_pow2(ways):
        raise ValueError(
            f"dataplane.sess_ways must be a power of two, got {ways}")
    if ways > c.sess_slots:
        raise ValueError(
            f"dataplane.sess_ways ({ways}) exceeds sess_slots "
            f"({c.sess_slots})")
    nns = int(getattr(c, "natsess_slots", 0) or 0)
    if nns and not _is_pow2(nns):
        raise ValueError(
            f"dataplane.natsess_slots must be a power of two (or 0 = "
            f"sess_slots), got {nns}")
    if nns and ways > nns:
        raise ValueError(
            f"dataplane.sess_ways ({ways}) exceeds natsess_slots ({nns})")
    if stride < 0 or (stride and not _is_pow2(stride)):
        raise ValueError(
            f"dataplane.sess_sweep_stride must be 0 (disabled) or a "
            f"power of two, got {stride}")
    fib_impl = getattr(c, "fib_impl", "auto")
    if fib_impl not in ("dense", "lpm", "pallas", "auto"):
        raise ValueError(
            f"dataplane.fib_impl must be dense | lpm | pallas | auto, "
            f"got {fib_impl!r}")
    session_impl = getattr(c, "session_impl", "auto")
    if session_impl not in ("gather", "pallas", "auto"):
        raise ValueError(
            f"dataplane.session_impl must be gather | pallas | auto, "
            f"got {session_impl!r}")
    sess_hash = getattr(c, "sess_hash", "fwd")
    if sess_hash not in ("fwd", "sym"):
        raise ValueError(
            f"dataplane.sess_hash must be fwd | sym, got {sess_hash!r}")
    if int(getattr(c, "fib_lpm_min_routes", 256)) < 0:
        raise ValueError(
            f"dataplane.fib_lpm_min_routes must be >= 0, got "
            f"{c.fib_lpm_min_routes}")
    caps = tuple(getattr(c, "fib_lpm_plen_caps", ()) or ())
    if len(caps) > 33:
        raise ValueError(
            f"dataplane.fib_lpm_plen_caps has {len(caps)} entries "
            f"(index = prefix length, max 33: /0../32)")
    for L, cap in enumerate(caps):
        if int(cap) < 0:
            raise ValueError(
                f"dataplane.fib_lpm_plen_caps[/{L}] must be >= 0, "
                f"got {cap}")
    eg = int(getattr(c, "fib_ecmp_groups", 0))
    if not (0 <= eg <= 4096):
        raise ValueError(
            f"dataplane.fib_ecmp_groups must be in 0..4096, got {eg}")
    ew = int(getattr(c, "fib_ecmp_ways", 8))
    if eg and (not _is_pow2(ew) or ew > 256):
        raise ValueError(
            f"dataplane.fib_ecmp_ways must be a power of two <= 256 "
            f"(the flow-hash member pick masks with W-1), got {ew}")
    ml_stage = getattr(c, "ml_stage", "off")
    if ml_stage not in ("off", "score", "enforce"):
        raise ValueError(
            f"dataplane.ml_stage must be off | score | enforce, got "
            f"{ml_stage!r}")
    if int(getattr(c, "ml_hidden", 16)) < 1:
        raise ValueError(
            f"dataplane.ml_hidden must be >= 1, got {c.ml_hidden}")
    if int(getattr(c, "ml_trees", 4)) < 1:
        raise ValueError(
            f"dataplane.ml_trees must be >= 1, got {c.ml_trees}")
    if not (1 <= int(getattr(c, "ml_depth", 3)) <= 8):
        raise ValueError(
            f"dataplane.ml_depth must be in 1..8 (leaf table is "
            f"2^depth), got {c.ml_depth}")
    tel = getattr(c, "telemetry", "off")
    if tel not in ("off", "latency", "full"):
        raise ValueError(
            f"dataplane.telemetry must be off | latency | full, got "
            f"{tel!r}")
    nb = int(getattr(c, "telemetry_lat_buckets", 24))
    if not (4 <= nb <= 31):
        raise ValueError(
            f"dataplane.telemetry_lat_buckets must be in 4..31 "
            f"(log2 µs bins in int32), got {nb}")
    d = int(getattr(c, "telemetry_sketch_rows", 2))
    if not (1 <= d <= 8):
        raise ValueError(
            f"dataplane.telemetry_sketch_rows must be in 1..8, got {d}")
    w = int(getattr(c, "telemetry_sketch_cols", 1024))
    if not _is_pow2(w):
        raise ValueError(
            f"dataplane.telemetry_sketch_cols must be a power of two "
            f"(column masking), got {w}")
    k = int(getattr(c, "telemetry_topk", 8))
    if not (1 <= k <= 64):
        raise ValueError(
            f"dataplane.telemetry_topk must be in 1..64, got {k}")
    tnt = getattr(c, "tenancy", "off")
    if tnt not in ("off", "on"):
        raise ValueError(
            f"dataplane.tenancy must be off | on, got {tnt!r}")
    t = int(getattr(c, "tenancy_tenants", 8))
    if not (1 <= t <= 64):
        raise ValueError(
            f"dataplane.tenancy_tenants must be in 1..64, got {t}")
    s = int(getattr(c, "tenancy_prefixes", 64))
    if not (1 <= s <= 1024):
        raise ValueError(
            f"dataplane.tenancy_prefixes must be in 1..1024, got {s}")
    ovl = getattr(c, "overlay", "off")
    if ovl not in ("off", "vxlan"):
        raise ValueError(
            f"dataplane.overlay must be off | vxlan, got {ovl!r}")
    v = int(getattr(c, "svc_vips", 0))
    if not (0 <= v <= 4096):
        raise ValueError(
            f"dataplane.svc_vips must be in 0..4096, got {v}")
    b = int(getattr(c, "svc_backend_ways", 8))
    if not _is_pow2(b) or b > 256:
        raise ValueError(
            f"dataplane.svc_backend_ways must be a power of two <= 256 "
            f"(the flow-hash backend pick masks with B-1), got {b}")


def ml_capacity(config: DataplaneConfig) -> Tuple[int, int, int, int]:
    """(features, hidden, trees, depth) capacity of the staged ML
    model arrays. With ml_stage "off" the fields carry minimal
    placeholder shapes (the BV allocation-gating pattern) — the stage
    is compiled out, so the placeholders are never read."""
    from vpp_tpu.ops.mlscore import ML_FEATURES

    if getattr(config, "ml_stage", "off") == "off":
        return ML_FEATURES, 1, 1, 1
    return (ML_FEATURES, int(getattr(config, "ml_hidden", 16)),
            int(getattr(config, "ml_trees", 4)),
            int(getattr(config, "ml_depth", 3)))


def empty_ml(config: DataplaneConfig) -> Dict[str, np.ndarray]:
    """Zero (no-model) ML staging arrays at the config's capacity.
    glb_ml_thresh defaults to INT32_MAX so even a kernel compiled with
    the stage on flags nothing until a model is staged (belt to the
    kind==NONE re-gate's braces)."""
    f, h, t, d = ml_capacity(config)
    return {
        "glb_ml_w1": np.zeros((f, h), np.int8),
        "glb_ml_b1": np.zeros(h, np.int32),
        "glb_ml_s1": np.int32(0),
        "glb_ml_w2": np.zeros(h, np.int8),
        "glb_ml_b2": np.int32(0),
        "glb_ml_f_feat": np.zeros((t, d), np.int32),
        "glb_ml_f_thresh": np.zeros((t, d), np.int32),
        "glb_ml_f_leaf": np.zeros((t, 1 << d), np.int32),
        "glb_ml_thresh": np.int32(0x7FFFFFFF),
        "glb_ml_action": np.int32(0),
        "glb_ml_rl_shift": np.int32(0),
        "glb_ml_version": np.int32(0),
    }


def _fold_ml(model, config: DataplaneConfig) -> Tuple[Dict[str, np.ndarray], int]:
    """Validate one MlModel against the config capacity and produce
    the padded, zero-point-FOLDED staging arrays (+ the staged kind).

    Validates COMPLETELY before returning — the builder only assigns
    the result, so a refused model can never leave staging
    half-mutated (the loader's keep-serving-the-previous-epoch
    contract). The fold: device features are int8 ``x - 128``, so each
    integer bias absorbs ``+128 * column_sum(W)``; exact in integers,
    pinned bit-exact against the unfolded oracle by
    tests/test_ml_stage.py."""
    from vpp_tpu.ml.model import MlModel, MlModelError
    from vpp_tpu.ops.mlscore import (
        ML_ACTION_NAMES,
        ML_KIND_FOREST,
        ML_KIND_MLP,
    )

    if isinstance(model, dict):
        model = MlModel.from_dict(model)
    model.validate()
    f, h, t, d = ml_capacity(config)
    if model.n_features > f:
        raise MlModelError(
            f"model has {model.n_features} features, pipeline computes "
            f"{f}")
    out = empty_ml(config)
    action_code = {name: code for code, name
                   in ML_ACTION_NAMES.items()}[model.action]
    if model.kind == "mlp":
        mh = model.hidden
        if mh > h:
            raise MlModelError(
                f"model hidden {mh} exceeds dataplane.ml_hidden {h}")
        w1 = np.zeros((f, h), np.int8)
        w1[: model.n_features, :mh] = model.w1
        b1 = np.zeros(h, np.int32)
        # the zero-point fold, layer 1: +128 per centered input column
        b1[:mh] = model.b1.astype(np.int64) + 128 * model.w1.astype(
            np.int64).sum(axis=0)
        # padding columns keep bias 0 => relu(0) = 0 => q1 = 0; their
        # centered form contributes -128 * w2_pad = 0 (w2 padding is 0)
        w2 = np.zeros(h, np.int8)
        w2[:mh] = model.w2
        # layer-2 fold: q1c = q1 - 128 over ALL h columns (padding
        # included — q1 of a padding column is 0, centered -128, times
        # its zero weight = 0, so folding over mh columns is exact)
        b2 = int(model.b2) + 128 * int(
            model.w2.astype(np.int64).sum())
        out.update(
            glb_ml_w1=w1, glb_ml_b1=b1, glb_ml_s1=np.int32(model.s1),
            glb_ml_w2=w2, glb_ml_b2=np.int32(b2))
        kind = ML_KIND_MLP
    else:
        mt, md = model.trees, model.depth
        if mt > t or md > d:
            raise MlModelError(
                f"forest {mt}x{md} exceeds dataplane.ml_trees/ml_depth "
                f"{t}x{d}")
        f_feat = np.zeros((t, d), np.int32)
        f_thresh = np.full((t, d), 255, np.int32)  # pad bits never set
        f_leaf = np.zeros((t, 1 << d), np.int32)
        f_feat[:mt, :md] = model.f_feat
        f_thresh[:mt, :md] = model.f_thresh
        # pad levels always test feature 0 > 255 => bit 0, so a padded
        # tree's leaf index only spans the model's 2^md prefix
        f_leaf[:mt, : 1 << md] = model.f_leaf
        out.update(
            glb_ml_f_feat=f_feat, glb_ml_f_thresh=f_thresh,
            glb_ml_f_leaf=f_leaf, glb_ml_b2=np.int32(model.b2))
        kind = ML_KIND_FOREST
    out.update(
        glb_ml_thresh=np.int32(model.flag_thresh),
        glb_ml_action=np.int32(action_code),
        glb_ml_rl_shift=np.int32(model.rl_shift),
        glb_ml_version=np.int32(model.version),
    )
    return out, kind


def pack_rules(rules: Sequence[ContivRule], max_rules: int) -> Dict[str, np.ndarray]:
    """Compile an ordered ContivRule list into padded match arrays.

    Rules must already be in evaluation order (most specific first — the
    ContivRuleTable invariant); first match wins in the kernel. Padding
    rows can never match (impossible port range, proto -2).

    Single Python pass gathering scalars + vectorized array fill: the
    original per-row array-store loop was the dominant host cost of a
    10k-rule commit (~17 ms), ahead of the bit-plane compile.
    """
    n = len(rules)
    if n > max_rules:
        raise ValueError(f"{n} rules exceed table capacity {max_rules}")
    out = _empty_packed(max_rules)
    if not n:
        return out
    rows = np.empty((n, 10), np.int64)
    for i, r in enumerate(rules):
        rows[i] = _rule_row(r)
    _fill_packed(out, rows, n)
    return out


def _empty_packed(max_rules: int) -> Dict[str, np.ndarray]:
    """All-padding match arrays (rows that can never match)."""
    return {
        "src_net": np.zeros(max_rules, np.uint32),
        "src_mask": np.zeros(max_rules, np.uint32),
        "dst_net": np.zeros(max_rules, np.uint32),
        "dst_mask": np.zeros(max_rules, np.uint32),
        "proto": np.full(max_rules, -2, np.int32),
        "sport_lo": np.ones(max_rules, np.int32),
        "sport_hi": np.zeros(max_rules, np.int32),
        "dport_lo": np.ones(max_rules, np.int32),
        "dport_hi": np.zeros(max_rules, np.int32),
        "action": np.full(max_rules, -1, np.int32),
    }


def _fill_packed(out: Dict[str, np.ndarray], rows: np.ndarray,
                 n: int) -> None:
    # out's insertion order IS the row-tuple order — one source of truth
    for j, (name, arr) in enumerate(out.items()):
        arr[:n] = rows[:, j].astype(arr.dtype)


def _rule_row(r: ContivRule) -> tuple:
    """One rule's 10-value match row (pack_rules layout)."""
    # IPv6 is a DESIGNED limitation of this v4 data plane (README
    # "Scope"): non-IPv4 frames never enter the classifier — the IO
    # front-end punts them to the host path — so a v6 rule can never
    # influence a verdict here. Skip it (row stays never-match)
    # instead of failing the whole table commit; enforcement for v6
    # belongs to the host stack that terminates that traffic.
    if (r.src_network is not None and r.src_network.version != 4) or (
        r.dest_network is not None and r.dest_network.version != 4
    ):
        log.warning("skipping IPv6 rule in v4 table: %s", r)
        return (0, 0, 0, 0, -2, 1, 0, 1, 0, -1)  # never-match row
    if r.src_network is not None:
        sm = _mask_of(r.src_network.prefixlen)
        sn = int(r.src_network.network_address) & sm
    else:
        sm = sn = 0
    if r.dest_network is not None:
        dm = _mask_of(r.dest_network.prefixlen)
        dn = int(r.dest_network.network_address) & dm
    else:
        dm = dn = 0
    sp, dp = r.src_port, r.dest_port
    return (
        sn, sm, dn, dm, r.protocol.ip_proto,
        0 if sp == ANY_PORT else sp, 65535 if sp == ANY_PORT else sp,
        0 if dp == ANY_PORT else dp, 65535 if dp == ANY_PORT else dp,
        int(r.action),
    )


def pack_rules_incremental(
    rules: Sequence[ContivRule],
    max_rules: int,
    prev_rules: Optional[list],
    prev_rows: Optional[np.ndarray],
) -> Tuple[Dict[str, np.ndarray], np.ndarray, Optional[np.ndarray]]:
    """pack_rules with an identity diff against the previous commit.

    Policy churn hands the builder a full rule list per commit, but
    unchanged entries are the SAME frozen ContivRule objects (the
    renderer cache reuses them) — so ``new[i] is old[i]`` finds the
    rows whose match columns must be recomputed, and everything else
    copies from ``prev_rows``. Rules that shift position (an
    insert/remove earlier in the list) fail the identity check at
    their new index and are simply recomputed — correctness never
    depends on the caller's reuse discipline, only the speedup does.

    Returns ``(packed, rows, changed)``: ``rows`` is the cache for the
    next call; ``changed`` is the sorted index array of rows that
    differ from the previous commit INCLUDING previously-live rows now
    past the end of the table (their bit-plane columns must revert to
    padding), or None when there was no usable previous state (full
    recompile)."""
    n = len(rules)
    if n > max_rules:
        raise ValueError(f"{n} rules exceed table capacity {max_rules}")
    rows = np.empty((n, 10), np.int64)
    if prev_rules is None or prev_rows is None:
        changed = None  # cold start: everything recompiles
        for i, r in enumerate(rules):
            rows[i] = _rule_row(r)
    else:
        m = len(prev_rules)
        changed_idx = []
        for i, r in enumerate(rules):
            if i < m and r is prev_rules[i]:
                rows[i] = prev_rows[i]
            else:
                rows[i] = _rule_row(r)
                changed_idx.append(i)
        # rows that existed last commit but are past the new end: their
        # packed slots revert to padding below, and their bit-plane
        # columns must be recompiled to never-match
        changed_idx.extend(range(n, m))
        changed = np.asarray(changed_idx, np.int64)
    packed = _empty_packed(max_rules)
    if n:
        _fill_packed(packed, rows, n)
    return packed, rows, changed


# Global-table fields in ROW space [R] (diffed/updated together; the
# bit-plane fields live in COLUMN space [R'] and diff separately).
_GLB_ROW_FIELDS: Tuple[str, ...] = (
    "glb_src_net", "glb_src_mask", "glb_dst_net", "glb_dst_mask",
    "glb_proto", "glb_sport_lo", "glb_sport_hi", "glb_dport_lo",
    "glb_dport_hi", "glb_action",
)


@functools.lru_cache(maxsize=16)
def _glb_update_fn(w_r: int, w_c: int, planes: int):
    """Jitted incremental global-table update for (row-block w_r,
    column-block w_c): ONE packed int32 blob upload carries every
    changed block, and one compiled program scatters the blocks into
    the cached device arrays with dynamic_update_slice (traced start
    offsets — no recompile per position). Blob layout:
    [10 x w_r rows | w_c k | w_c act | planes x w_c coeff]."""
    import jax

    def update(rows, k, act, coeff, blob, lo_r, lo_c):
        from jax import lax

        out_rows = []
        for i, dev in enumerate(rows):
            piece = lax.bitcast_convert_type(
                blob[i * w_r:(i + 1) * w_r], dev.dtype
            )
            out_rows.append(lax.dynamic_update_slice(dev, piece, (lo_r,)))
        base = 10 * w_r
        k_piece = lax.bitcast_convert_type(
            blob[base:base + w_c], jnp.float32
        )
        new_k = lax.dynamic_update_slice(k, k_piece, (lo_c,))
        act_piece = blob[base + w_c:base + 2 * w_c]
        new_act = lax.dynamic_update_slice(act, act_piece, (lo_c,))
        coeff_piece = lax.bitcast_convert_type(
            blob[base + 2 * w_c:base + 2 * w_c + planes * w_c],
            jnp.float32,
        ).reshape(planes, w_c)
        new_coeff = lax.dynamic_update_slice(
            coeff, coeff_piece, (0, lo_c)
        )
        return out_rows, new_k, new_act, new_coeff

    return jax.jit(update)


def _block_of(changed: np.ndarray, total: int) -> Optional[Tuple[int, int]]:
    """(lo, width) of the smallest padded block covering every changed
    index, widths on a x4 ladder; None when nothing changed."""
    idx = np.nonzero(changed)[0]
    if len(idx) == 0:
        return None
    lo, hi = int(idx[0]), int(idx[-1]) + 1
    span = hi - lo
    w = 256
    while w < span:
        w *= 4
    if w >= total:
        return 0, total
    lo = min(lo, total - w)
    return lo, w


# Upload groups: which DataplaneTables fields each builder mutation
# invalidates. to_device() re-uploads only dirty groups; the rest reuse
# the previous epoch's device arrays (the big win: a CNI add doesn't
# re-ship the multi-MB 10k-rule bit-plane matrix).
_UPLOAD_GROUPS: Dict[str, Tuple[str, ...]] = {
    "acl": ("acl_src_net", "acl_src_mask", "acl_dst_net", "acl_dst_mask",
            "acl_proto", "acl_sport_lo", "acl_sport_hi", "acl_dport_lo",
            "acl_dport_hi", "acl_action", "acl_nrules",
            "acl_bv_bnd_src", "acl_bv_bnd_dst", "acl_bv_bnd_sport",
            "acl_bv_bnd_dport", "acl_bv_nbnd", "acl_bv_src",
            "acl_bv_dst", "acl_bv_sport", "acl_bv_dport",
            "acl_bv_proto"),
    "glb": ("glb_src_net", "glb_src_mask", "glb_dst_net", "glb_dst_mask",
            "glb_proto", "glb_sport_lo", "glb_sport_hi", "glb_dport_lo",
            "glb_dport_hi", "glb_action", "glb_nrules", "glb_mxu_coeff",
            "glb_mxu_k", "glb_mxu_act"),
    # the BV structure uploads per-dimension-plane (see to_device): a
    # separate group so the "glb" incremental row/column blob path can
    # never leave stale BV planes on the device
    "glb_bv": ("glb_bv_bnd_src", "glb_bv_bnd_dst", "glb_bv_bnd_sport",
               "glb_bv_bnd_dport", "glb_bv_nbnd", "glb_bv_src",
               "glb_bv_dst", "glb_bv_sport", "glb_bv_dport",
               "glb_bv_proto"),
    # the ML model blob (set_ml_model): its OWN group so an epoch swap
    # re-ships it ONLY when the model actually changed — ACL/FIB/NAT
    # churn reuses the cached device arrays (zero re-ship, pinned by
    # tests/test_ml_stage.py), and a model swap ships ~a few hundred
    # int8 weights without touching the multi-MB rule planes
    "ml": ("glb_ml_w1", "glb_ml_b1", "glb_ml_s1", "glb_ml_w2",
           "glb_ml_b2", "glb_ml_f_feat", "glb_ml_f_thresh",
           "glb_ml_f_leaf", "glb_ml_thresh", "glb_ml_action",
           "glb_ml_rl_shift", "glb_ml_version"),
    "if": ("if_type", "if_local_table", "if_apply_global"),
    # the FIB group uploads with per-field granularity (see to_device):
    # per-slot row arrays go through the incremental scatter-blob path
    # (_fib_incremental — a route flap ships a few-KB blob, not 9 x 4 MB
    # columns at the 1M-route regime), and the per-length LPM planes +
    # ECMP tables re-ship only when _fib_dirty names them (a flap =
    # ONE touched length plane + the count vector)
    "fib": ("fib_prefix", "fib_mask", "fib_plen", "fib_tx_if", "fib_disp",
            "fib_next_hop", "fib_node_id", "fib_snat", "fib_grp",
            "fib_lpm_p0", "fib_lpm_p1", "fib_lpm_p2", "fib_lpm_p3",
            "fib_lpm_p4", "fib_lpm_p5", "fib_lpm_p6", "fib_lpm_p7",
            "fib_lpm_p8", "fib_lpm_p9", "fib_lpm_p10", "fib_lpm_p11",
            "fib_lpm_p12", "fib_lpm_p13", "fib_lpm_p14", "fib_lpm_p15",
            "fib_lpm_p16", "fib_lpm_p17", "fib_lpm_p18", "fib_lpm_p19",
            "fib_lpm_p20", "fib_lpm_p21", "fib_lpm_p22", "fib_lpm_p23",
            "fib_lpm_p24", "fib_lpm_p25", "fib_lpm_p26", "fib_lpm_p27",
            "fib_lpm_p28", "fib_lpm_p29", "fib_lpm_p30", "fib_lpm_p31",
            "fib_lpm_p32", "fib_lpm_cnt", "fib_lpm_hint",
            "fib_grp_nh", "fib_grp_tx_if", "fib_grp_node", "fib_grp_n"),
    "nat": ("nat_ext_ip", "nat_ext_port", "nat_proto", "nat_boff",
            "nat_bcnt", "nat_total_w", "nat_self_snat", "natb_ip",
            "natb_port", "natb_cumw", "nat_snat_ip"),
    "config": ("sess_max_age", "ovl_vtep_ip"),
    # tenancy config half (ISSUE 14): its OWN group, so tenant churn
    # (a new prefix, a rate change, a per-tenant ML threshold flip)
    # ships a few hundred bytes and never re-ships rules or weights —
    # and vice versa. The tnt_* STATE planes are not here: they ride
    # the carry-over like the sweep cursors.
    "tenant": ("tnt_pfx_net", "tnt_pfx_mask", "tnt_pfx_id",
               "tnt_rate", "tnt_burst",
               "tnt_sess_base", "tnt_sess_mask",
               "tnt_nat_base", "tnt_nat_mask",
               "glb_ml_tnt_mode", "glb_ml_tnt_thresh", "tnt_vni"),
    # service NAT44 LB planes (ISSUE 19): their OWN group so a rolling
    # backend replacement ships ONLY svc bytes — every other group
    # keeps its cached device-array identity (the zero-reship
    # acceptance bench pins). Additionally rides the incremental
    # scatter-blob path (_upload_svc): changed VIP rows confine to a
    # block and ship as one few-KB blob.
    "svc": ("svc_vip_ip", "svc_vip_port", "svc_vip_proto",
            "svc_vip_snat", "svc_bk_n", "svc_bk_ip", "svc_bk_port"),
}

# Per-slot FIB row arrays (the dense kernel's columns + the shared
# resolver's route data): diffed together against _fib_prev and
# scatter-updated on device as ONE packed blob when a commit's changes
# confine to a block (_fib_incremental — the _glb_incremental scheme
# without the bit-plane column space).
_FIB_SLOT_FIELDS: Tuple[str, ...] = (
    "fib_prefix", "fib_mask", "fib_plen", "fib_tx_if", "fib_disp",
    "fib_next_hop", "fib_node_id", "fib_snat", "fib_grp",
)


@functools.lru_cache(maxsize=8)
def _fib_update_fn(w: int):
    """Jitted incremental per-slot FIB update for row-block width
    ``w``: one packed int32 blob carries every per-slot array's
    changed block, one compiled program scatters the blocks into the
    cached device arrays with dynamic_update_slice (traced start
    offset — no recompile per position). Blob layout: [9 x w rows]."""
    import jax

    def update(rows, blob, lo):
        from jax import lax

        out = []
        for i, dev in enumerate(rows):
            piece = lax.bitcast_convert_type(
                blob[i * w:(i + 1) * w], dev.dtype
            )
            out.append(lax.dynamic_update_slice(dev, piece, (lo,)))
        return out

    return jax.jit(update)


# Service-LB planes in VIP-row space (ISSUE 19): diffed together
# against _svc_prev and scatter-updated on device as ONE packed blob
# when a churn's changes confine to a row block (the _fib_incremental
# scheme; the [V, B] way tables flatten into the blob row-major).
_SVC_1D_FIELDS: Tuple[str, ...] = (
    "svc_vip_ip", "svc_vip_port", "svc_vip_proto", "svc_vip_snat",
    "svc_bk_n",
)
_SVC_2D_FIELDS: Tuple[str, ...] = ("svc_bk_ip", "svc_bk_port")


@functools.lru_cache(maxsize=8)
def _svc_update_fn(w: int, ways: int):
    """Jitted incremental service-plane update for VIP-row-block width
    ``w``: one packed int32 blob carries every svc array's changed row
    block, one compiled program scatters the blocks into the cached
    device arrays (traced start offset — no recompile per position).
    Blob layout: [5 x w rows | 2 x w x B way rows]."""
    import jax

    def update(rows, grids, blob, lo):
        from jax import lax

        out_rows = []
        for i, dev in enumerate(rows):
            piece = lax.bitcast_convert_type(
                blob[i * w:(i + 1) * w], dev.dtype
            )
            out_rows.append(lax.dynamic_update_slice(dev, piece, (lo,)))
        base = len(rows) * w
        out_grids = []
        for i, dev in enumerate(grids):
            piece = lax.bitcast_convert_type(
                blob[base + i * w * ways:base + (i + 1) * w * ways],
                dev.dtype,
            ).reshape(w, ways)
            out_grids.append(
                lax.dynamic_update_slice(dev, piece, (lo, 0)))
        return out_rows, out_grids

    return jax.jit(update)


# BV dimension -> its global-table device fields (granular upload:
# only the planes compile_bv actually rebuilt re-ship; the nbnd count
# vector rides along whenever anything changed).
_GLB_BV_DIM_FIELDS: Dict[str, Tuple[str, ...]] = {
    "src": ("glb_bv_bnd_src", "glb_bv_src"),
    "dst": ("glb_bv_bnd_dst", "glb_bv_dst"),
    "sport": ("glb_bv_bnd_sport", "glb_bv_sport"),
    "dport": ("glb_bv_bnd_dport", "glb_bv_dport"),
    "proto": ("glb_bv_proto",),
}


class TableBuilder:
    """Mutable host-side (numpy) staging area for the device tables.

    The TPU renderer and the node controller mutate this builder, then call
    ``to_device()`` to produce the immutable DataplaneTables pytree for the
    next epoch. Session state is *not* rebuilt: ``to_device`` can graft the
    live session arrays from a previous epoch so established flows survive
    table swaps.
    """

    def __init__(self, config: DataplaneConfig = DataplaneConfig()):
        validate_dataplane_config(config)
        self.config = config
        self.mxu_enabled = True  # opt-out knob for the bit-plane compile
        # api-trace analog (pipeline/txn.py): with recording started,
        # every mutator appends its declarative op here and the owning
        # Dataplane journals the batch at swap() — production writers
        # (renderers, CNI, service, node events) get recorded without
        # changing, exactly like VPP tracing at the binary-API boundary
        # (reference contiv-vswitch.conf:13-15 `api-trace { on }`).
        self._rec = None
        # optional writer-supplied label for the NEXT journaled txn
        self.txn_label = ""
        c = config
        z = np.zeros
        self.acl = {
            k: np.tile(v, (c.max_tables, 1))
            for k, v in pack_rules([], c.max_rules).items()
        }
        self.acl_nrules = z(c.max_tables, np.int32)
        self.glb = pack_rules([], c.max_global_rules)
        self.glb_nrules = 0
        from vpp_tpu.ops.acl_mxu import empty_bitplanes

        self.glb_mxu = empty_bitplanes(c.max_global_rules)
        # BV interval-bitmap staging (ops/acl_bv.py). Allocation is
        # knob-gated: dense/mxu configs (and auto configs whose
        # worst-case structure busts classifier_bv_mem_mb) carry only
        # minimal placeholder shapes — the BV kernels are then never
        # selected, so the placeholders are never read.
        from vpp_tpu.ops.acl_bv import bv_capacity, bv_enabled_for, empty_bv

        knob = getattr(c, "classifier", "auto")
        if knob not in ("dense", "mxu", "bv", "pallas", "auto"):
            # loud, at config time: a typo'd knob silently falling
            # through to the auto ladder would run a different
            # classifier than the operator believes is deployed
            raise ValueError(
                f"unknown dataplane.classifier {knob!r} "
                f"(expected dense | mxu | bv | pallas | auto)")
        self.bv_enabled = bv_enabled_for(c)
        self.glb_bv = empty_bv(c.max_global_rules, self.bv_enabled)
        self._bv_cols = None        # per-dim column cache (incremental)
        self._bv_dirty = set(_UPLOAD_GROUPS["glb_bv"])
        self.bv_rebuilt: Tuple[str, ...] = ()  # last commit's planes
        self.bv_build_ms = 0.0      # last commit's BV host build cost
        local_bv = empty_bv(c.max_rules, self.bv_enabled)
        lib, lw, lpr = bv_capacity(c.max_rules, self.bv_enabled)
        self.acl_bv = {
            "bnd_src": np.tile(local_bv.bnd_src, (c.max_tables, 1)),
            "bnd_dst": np.tile(local_bv.bnd_dst, (c.max_tables, 1)),
            "bnd_sport": np.tile(local_bv.bnd_sport, (c.max_tables, 1)),
            "bnd_dport": np.tile(local_bv.bnd_dport, (c.max_tables, 1)),
            "nbnd": np.tile(local_bv.nbnd, (c.max_tables, 1)),
            "src": np.zeros((c.max_tables, lib, lw), np.uint32),
            "dst": np.zeros((c.max_tables, lib, lw), np.uint32),
            "sport": np.zeros((c.max_tables, lib, lw), np.uint32),
            "dport": np.zeros((c.max_tables, lib, lw), np.uint32),
            "proto": np.zeros((c.max_tables, lpr, lw), np.uint32),
        }
        self.acl_bv_ok = np.ones(c.max_tables, bool)
        # per-packet ML model staging (ops/mlscore.py; docs/ML_STAGE.md):
        # zero/no-model arrays at the config capacity until
        # set_ml_model stages an artifact. ml_kind is the staged
        # model's kernel variant (ML_KIND_*; 0 = none — the Dataplane
        # re-gates the compiled stage off at swap while it is 0).
        self.ml = empty_ml(c)
        self.ml_kind = 0
        # multi-tenant gateway staging (ISSUE 14; vpp_tpu/tenancy/):
        # a normalized tenant-entry registry (set_tenant) compiled
        # into the "tenant" upload-group arrays by _restage_tenants.
        # The VNI → tenant map and the WFQ weights live in the
        # registry only — they are HOST-side knobs (the IO pump's
        # TenantClassifier), not device state.
        self.tenants: Dict[int, dict] = {}
        self.tnt: Dict[str, np.ndarray] = {}
        self._restage_tenants()
        self.if_type = z(c.max_ifaces, np.int32)
        self.if_local_table = np.full(c.max_ifaces, -1, np.int32)
        self.if_apply_global = z(c.max_ifaces, np.int32)
        self.fib_prefix = z(c.fib_slots, np.uint32)
        self.fib_mask = z(c.fib_slots, np.uint32)
        self.fib_plen = np.full(c.fib_slots, -1, np.int32)
        self.fib_tx_if = z(c.fib_slots, np.int32)
        self.fib_disp = np.full(c.fib_slots, int(Disposition.DROP), np.int32)
        self.fib_next_hop = z(c.fib_slots, np.uint32)
        self.fib_node_id = np.full(c.fib_slots, -1, np.int32)
        self.fib_snat = z(c.fib_slots, np.int32)
        self.fib_grp = np.full(c.fib_slots, -1, np.int32)
        # LPM per-length prefix planes (ops/lpm.py; ISSUE 15).
        # Allocation is knob-gated like BV: dense configs (and auto
        # configs whose worst-case planes bust fib_lpm_mem_mb) carry
        # zero-width placeholders — the LPM kernel is then never
        # selected. Staging is LAZY: mutators only mark the touched
        # LENGTH dirty; _restage_lpm() recompiles dirty planes at
        # host_arrays()/lpm_ok() time (one vectorized pass per dirty
        # length — a 1M-route bulk load pays 33 passes total, not one
        # per route).
        from vpp_tpu.ops.lpm import (
            LPM_LENGTHS,
            LPM_PAD,
            ecmp_capacity,
            lpm_enabled_for,
            lpm_field,
            lpm_hint_layout,
            lpm_len_caps,
        )

        self.lpm_enabled = lpm_enabled_for(c)
        self.lpm_caps = lpm_len_caps(c)
        self._lpm_layout, hint_rows = lpm_hint_layout(self.lpm_caps)
        self.lpm_hint = z(hint_rows, np.int32)
        self.lpm_planes = {}
        for length in range(LPM_LENGTHS):
            plane = np.zeros((2, self.lpm_caps[length]), np.uint32)
            plane[0, :] = LPM_PAD
            self.lpm_planes[lpm_field(length)] = plane
        self.lpm_cnt = z(LPM_LENGTHS, np.int32)
        # full per-length route counts (deduped, NOT clipped to caps —
        # the lpm_ok() overflow signal and the `show fib` histogram)
        self.lpm_counts = z(LPM_LENGTHS, np.int64)
        self._lpm_dirty_lens = set(range(LPM_LENGTHS))
        self.lpm_build_ms = 0.0   # host cost of the LAST plane restage
        # ECMP next-hop groups: registry {gid: {"members": [(nh,
        # tx_if, node), ...], "assign": [member per way]}} compiled
        # into the [G, W] member tables with STICKY way assignment
        # (set_nh_group) — member churn only reassigns the ways it
        # must, so flows hashed to surviving ways keep their member.
        gcap, ways = ecmp_capacity(c)
        self.nh_groups: Dict[int, dict] = {}
        self.fib_grp_nh = z((gcap, ways), np.uint32)
        self.fib_grp_tx_if = np.full((gcap, ways), -1, np.int32)
        self.fib_grp_node = np.full((gcap, ways), -1, np.int32)
        self.fib_grp_n = z(gcap, np.int32)
        # per-field dirty set of the "fib" upload group (the _bv_dirty
        # pattern): to_device re-ships only named fields; per-slot row
        # arrays additionally try the incremental scatter-blob path
        self._fib_dirty = set(_UPLOAD_GROUPS["fib"])
        # per-slot arrays as of the last full device upload (the
        # incremental diff base; None = next commit uploads full)
        self._fib_prev: Optional[Dict[str, np.ndarray]] = None
        # last fib-group upload, for `show fib` / fib_bench: fields
        # re-shipped, bytes, host ms ("blob" = the per-slot scatter)
        self.fib_upload: Dict[str, object] = {}
        self.fib_last_shipped = False
        self.nat_ext_ip = z(c.nat_mappings, np.uint32)
        self.nat_ext_port = z(c.nat_mappings, np.int32)
        self.nat_proto = z(c.nat_mappings, np.int32)
        self.nat_boff = z(c.nat_mappings, np.int32)
        self.nat_bcnt = z(c.nat_mappings, np.int32)
        self.nat_total_w = z(c.nat_mappings, np.int32)
        self.nat_self_snat = z(c.nat_mappings, np.int32)
        self.natb_ip = z(c.nat_backends, np.uint32)
        self.natb_port = z(c.nat_backends, np.int32)
        self.natb_cumw = z(c.nat_backends, np.int32)
        self.nat_snat_ip = np.uint32(0)
        # VXLAN overlay config (ISSUE 19): the node's local VTEP
        # address, staged into the tiny "config" group.
        self.ovl_vtep_ip = np.uint32(0)
        # Service NAT44 LB staging (ISSUE 19): a normalized service
        # registry (set_service) compiled into the "svc" upload-group
        # arrays by _restage_svc — the tenant-registry pattern. Each
        # entry keeps its sticky way ASSIGNMENT, keyed by the service
        # key, so VIP-row moves from churn elsewhere never reshuffle a
        # surviving service's backend picks.
        self.services: Dict[Tuple[int, int, int], dict] = {}
        self.svc: Dict[str, np.ndarray] = {}
        self._restage_svc()
        # svc incremental-upload state (the _fib_prev discipline):
        # diff base of the last full device upload (None = next commit
        # uploads full) + last-upload record for `show services` /
        # overlay_bench's svc_churn_bytes.
        self._svc_prev: Optional[Dict[str, np.ndarray]] = None
        self.svc_upload: Dict[str, object] = {}
        self.svc_last_shipped = False
        # Upload groups touched since the last to_device(): every field
        # of a clean group reuses the previous epoch's DEVICE array, so
        # a CNI add (fib+if dirty) doesn't re-upload the 10k-rule
        # bit-plane matrix — each host→device transfer is a full RPC
        # round trip on a remote transport (VERDICT r2 Weak #4).
        self._dirty = set(_UPLOAD_GROUPS)
        self._dev_cache: Dict[str, object] = {}
        # host arrays as of the last SUCCESSFUL device upload of the
        # "glb" group: the diff base for incremental column/row-block
        # commits (row arrays copied — see _set_glb_prev).
        self._glb_prev: Optional[Dict[str, np.ndarray]] = None
        # incremental global-table HOST compile (VERDICT r4 Next #3):
        # the renderer hands a full rule list per commit but reuses
        # unchanged frozen ContivRule objects, so an identity diff
        # (pack_rules_incremental) finds the churned rows and only
        # their match rows + bit-plane columns are recomputed.
        # Invalidated (None) whenever glb state changes by any path
        # other than set_global_table (snapshot restore).
        self._glb_rules_ref: Optional[list] = None
        self._glb_rows: Optional[np.ndarray] = None
        self._glb_bad: Optional[np.ndarray] = None

    def _mark(self, group: str) -> None:
        self._dirty.add(group)

    # --- op recording (config transaction trace) ---
    def start_recording(self) -> None:
        from vpp_tpu.pipeline.txn import ConfigTxn

        if self._rec is None:
            self._rec = ConfigTxn()

    def drain_recording(self):
        """Ops recorded since the last drain as one ConfigTxn (None when
        recording is off or nothing was staged). Consumes the pending
        ``txn_label``. Called by swap() under the commit lock."""
        from vpp_tpu.pipeline.txn import ConfigTxn

        if self._rec is None or not self._rec.ops:
            self.txn_label = ""
            return None
        txn = self._rec
        txn.label = self.txn_label
        self.txn_label = ""
        self._rec = ConfigTxn()
        return txn

    def bv_ok(self) -> bool:
        """Whether the BV classifier can serve THIS staged config:
        structure allocated, and every table (global + all local
        slots) expressible as interval bitmaps (no non-prefix masks)."""
        return (self.bv_enabled and self.glb_bv.ok
                and bool(self.acl_bv_ok.all()))

    # --- ACL ---
    def set_local_table(self, slot: int, rules: Sequence[ContivRule]) -> None:
        packed = pack_rules(rules, self.config.max_rules)
        for k, v in packed.items():
            self.acl[k][slot] = v
        self.acl_nrules[slot] = len(rules)
        if self.bv_enabled:
            # per-slot full rebuild: local tables are <= max_rules
            # (128) rows, so the plane compile is microseconds — the
            # dimension-incremental path only pays off at global scale
            from vpp_tpu.ops.acl_bv import compile_bv

            bv, _, _ = compile_bv(packed, self.config.max_rules)
            for dim in ("src", "dst", "sport", "dport"):
                self.acl_bv[f"bnd_{dim}"][slot] = getattr(bv, f"bnd_{dim}")
                self.acl_bv[dim][slot] = getattr(bv, f"bm_{dim}")
            self.acl_bv["nbnd"][slot] = bv.nbnd
            self.acl_bv["proto"][slot] = bv.bm_proto
            self.acl_bv_ok[slot] = bv.ok
        if self._rec is not None:
            self._rec.set_local_table(slot, rules)
        self._mark("acl")

    def clear_local_table(self, slot: int) -> None:
        self.set_local_table(slot, [])

    def set_global_table(self, rules: Sequence[ContivRule]) -> None:
        from vpp_tpu.ops.acl_mxu import (
            compile_bitplanes_full,
            compile_bitplanes_update,
            empty_bitplanes,
        )

        cap = self.config.max_global_rules
        packed, rows, changed = pack_rules_incremental(
            rules, cap, self._glb_rules_ref, self._glb_rows)
        self.glb = packed
        self.glb_nrules = len(rules)
        if self._rec is not None:
            self._rec.set_global_table(rules)
        # mxu_enabled=False skips the O(PLANES·R) host-side bit-plane
        # compile for callers that will never take the MXU path. (The
        # zero coeff matrix is still part of the pytree — shapes must
        # stay epoch-invariant for jit — so the device upload itself is
        # not avoided, only the host work.)
        #
        # The identity caches are persisted only AFTER a successful
        # compile: caching them first would let a compile exception
        # (e.g. MemoryError on the coeff matrix) poison the diff base —
        # a retried commit with the same rule objects would see
        # changed=[] and carry the STALE bit-planes forward silently.
        try:
            if not self.mxu_enabled:
                self.glb_mxu = empty_bitplanes(cap)
                bad = None  # forces a full compile if re-enabled
            elif changed is None or self._glb_bad is None:
                self.glb_mxu, bad = compile_bitplanes_full(self.glb, cap)
            else:
                # policy churn: only the changed rule columns recompile
                self.glb_mxu, bad = compile_bitplanes_update(
                    self.glb, cap, self.glb_mxu, self._glb_bad, changed)
            if self.bv_enabled:
                # dimension-incremental BV compile (ops/acl_bv.py):
                # composes with the identity-diff pack above — only
                # dimension planes whose per-rule intervals actually
                # moved rebuild; a port-only churn keeps the (large)
                # address bitmaps untouched on host AND device
                from vpp_tpu.ops.acl_bv import compile_bv

                # upload-ok: compile_bv reuses the prev planes for
                # every dimension it did not rebuild, so when
                # `rebuilt` is empty the device copies are still
                # content-identical and skipping the glb_bv mark is
                # the zero-reship design, not a staleness gap; any
                # rebuilt dimension marks the group two lines down
                self.glb_bv, self._bv_cols, rebuilt = compile_bv(
                    self.glb, cap, prev=self.glb_bv,
                    prev_cols=self._bv_cols)
                self.bv_rebuilt = rebuilt
                self.bv_build_ms = self.glb_bv.build_ms
                if rebuilt:
                    self._bv_dirty.add("glb_bv_nbnd")
                    for dim in rebuilt:
                        self._bv_dirty.update(_GLB_BV_DIM_FIELDS[dim])
                    self._mark("glb_bv")
        except Exception:
            self._glb_rules_ref = None
            self._glb_rows = None
            self._glb_bad = None
            self._bv_cols = None
            self._bv_dirty = set(_UPLOAD_GROUPS["glb_bv"])
            raise
        self._glb_rules_ref = list(rules)
        self._glb_rows = rows
        self._glb_bad = bad
        self._mark("glb")

    # --- per-packet ML model (ops/mlscore.py) ---
    def set_ml_model(self, model) -> None:
        """Stage one quantized model (an MlModel or its dict form —
        vpp_tpu/ml/model.py) for the next epoch. Validation + padding
        + the zero-point fold all happen in ``_fold_ml`` BEFORE any
        staging state mutates, so a refused artifact (bad shape, bad
        version, capacity overflow) leaves the previous model serving
        — the loader's clean-refusal contract (vpp_tpu/ml/loader.py).
        Marks only the "ml" upload group: rule churn and model churn
        re-ship independently."""
        staged, kind = _fold_ml(model, self.config)
        self.ml = staged
        self.ml_kind = kind
        if self._rec is not None:
            self._rec.set_ml_model(model)
        self._mark("ml")

    def clear_ml_model(self) -> None:
        """Back to the no-model state (the stage re-gates off at the
        next swap)."""
        self.ml = empty_ml(self.config)
        self.ml_kind = 0
        if self._rec is not None:
            self._rec.clear_ml_model()
        self._mark("ml")

    # --- multi-tenant gateway (ISSUE 14; vpp_tpu/tenancy/) ---
    def _restage_tenants(self) -> None:
        """Compile the tenant registry into the "tenant" upload-group
        arrays. Session/NAT bucket slices are allocated contiguously
        in ascending tenant-id order from the TOP of the table
        downward (GLOBAL bucket units — the mesh's bucket-axis shards
        split any global index, so slices compose with the partition
        layer unchanged); unsliced tenants (including the implicit
        default tenant 0) share the residual BOTTOM region, masked to
        the largest power of two that fits — disjoint from every
        slice, so unsliced traffic can never hash into (let alone
        evict from) a sliced tenant's range. With nothing sliced the
        residual is the whole table: bit-identical to the unsliced
        ``_hash``. Deterministic: the same registry always compiles
        byte-identical arrays."""
        c = self.config
        T, S = tnt_capacity(c)
        ways = int(getattr(c, "sess_ways", 4))
        sess_nb = c.sess_slots // ways
        nat_nb = natsess_slots_of(c) // ways
        net = np.zeros(S, np.uint32)
        mask = np.zeros(S, np.uint32)
        pid = np.full(S, -1, np.int32)
        rate = np.zeros(T, np.int32)
        burst = np.zeros(T, np.int32)
        sb = np.zeros(T, np.int32)
        sm = np.zeros(T, np.int32)
        nb_ = np.zeros(T, np.int32)
        nm = np.zeros(T, np.int32)
        mlm = np.zeros(T, np.int32)
        mlt = np.full(T, ML_TNT_THRESH_INHERIT, np.int32)
        # VNI → tenant plane (ISSUE 19): tenant t's registered VNI or
        # -1. Tenancy-off placeholder admits DEFAULT_VNI as tenant 0 so
        # the single-tenant overlay works out of the box; every other
        # VNI fails closed at decap.
        from vpp_tpu.ops.vxlan import DEFAULT_VNI  # local: keeps the
        # tables module importable without pulling the overlay ops in
        # at module load (the sched-import discipline)

        vni = np.full(T, -1, np.int32)
        if getattr(c, "tenancy", "off") == "off":
            vni[0] = DEFAULT_VNI
        slot = 0
        cursor = {"sess": sess_nb, "nat": nat_nb}
        sliced_tids = {"sess": set(), "nat": set()}
        from vpp_tpu.tenancy.sched import ML_MODE_CODES  # jax-free

        for tid in sorted(self.tenants):
            e = self.tenants[tid]
            for p in e["prefixes"]:
                if slot >= S:
                    raise ValueError(
                        f"tenant prefix map full ({S} slots — raise "
                        f"dataplane.tenancy_prefixes)")
                pnet = ipaddress.ip_network(p, strict=False)
                m = _mask_of(pnet.prefixlen)
                net[slot] = int(pnet.network_address) & m
                mask[slot] = m
                pid[slot] = tid
                slot += 1
            rate[tid] = e["rate"]
            burst[tid] = e["burst"]
            for kind, basearr, maskarr in (
                    ("sess", sb, sm), ("nat", nb_, nm)):
                nbk = e[f"{kind}_buckets"]
                if nbk:
                    cursor[kind] -= nbk
                    basearr[tid] = cursor[kind]
                    maskarr[tid] = nbk - 1
                    sliced_tids[kind].add(tid)
            mlm[tid] = ML_MODE_CODES[e.get("ml_mode", "inherit")]
            if e.get("ml_thresh") is not None:
                mlt[tid] = int(e["ml_thresh"])
            if e.get("vni") is not None:
                vni[tid] = int(e["vni"])
        # unsliced tenants (every tid not sliced above, tenant 0
        # included unless it registered a slice): base 0, masked to
        # the largest power of two inside the residual [0, cursor) so
        # they can never land in a slice. validate_tenancy_config
        # guarantees cursor > 0 whenever an unsliced tenant exists.
        for kind, maskarr in (("sess", sm), ("nat", nm)):
            free = cursor[kind]
            um = (1 << (free.bit_length() - 1)) - 1 if free > 0 else 0
            for tid in range(T):
                if tid not in sliced_tids[kind]:
                    maskarr[tid] = um
        self.tnt = {
            "tnt_pfx_net": net, "tnt_pfx_mask": mask, "tnt_pfx_id": pid,
            "tnt_rate": rate, "tnt_burst": burst,
            "tnt_sess_base": sb, "tnt_sess_mask": sm,
            "tnt_nat_base": nb_, "tnt_nat_mask": nm,
            "glb_ml_tnt_mode": mlm, "glb_ml_tnt_thresh": mlt,
            "tnt_vni": vni,
        }

    def set_tenant(self, tid: int, **kw) -> None:
        """Register (or replace) one tenant: prefixes, VNI, token
        bucket (``rate`` tokens/tick, ``burst`` capacity), session/NAT
        capacity slices (``sess_buckets``/``nat_buckets`` — power-of-2
        bucket counts; 0 = unsliced), the pump's WFQ ``weight``, and
        the per-tenant ML override (``ml_mode``/``ml_thresh``).
        Validated as a whole (vpp_tpu/tenancy/sched.py) so an
        oversubscribed slice or a bad prefix is refused BEFORE any
        staging mutates."""
        if getattr(self.config, "tenancy", "off") == "off":
            raise ValueError(
                "dataplane.tenancy is off — set_tenant requires "
                "tenancy: on (the tnt_* planes carry placeholder "
                "shapes otherwise)")
        from vpp_tpu.tenancy.sched import validate_tenancy_config

        merged = {t: dict(e) for t, e in self.tenants.items()}
        merged[int(tid)] = {"id": int(tid), **kw}
        entries = validate_tenancy_config(self.config,
                                          list(merged.values()))
        self.tenants = {e["id"]: e for e in entries}
        self._restage_tenants()
        if self._rec is not None:
            self._rec.set_tenant(int(tid), **kw)
        self._mark("tenant")

    def clear_tenants(self) -> None:
        """Back to the single default tenant (everything tenant 0,
        unsliced, unlimited)."""
        self.tenants = {}
        self._restage_tenants()
        if self._rec is not None:
            self._rec.clear_tenants()
        self._mark("tenant")

    def set_tenant_ml(self, tid: int, ml_mode: str = "inherit",
                      ml_thresh: Optional[int] = None) -> None:
        """Flip ONE tenant's ML mode/threshold without touching its
        other staging — marks only the "tenant" group, so the model's
        weight planes re-ship NOTHING (the ISSUE 14 satellite: tenants
        run different off|score|enforce modes against one staged
        model)."""
        if int(tid) not in self.tenants:
            raise ValueError(
                f"tenant {tid} not registered (set_tenant first)")
        e = dict(self.tenants[int(tid)])
        e["ml_mode"] = ml_mode
        e["ml_thresh"] = ml_thresh
        from vpp_tpu.tenancy.sched import validate_tenancy_config

        merged = {t: dict(x) for t, x in self.tenants.items()}
        merged[int(tid)] = e
        entries = validate_tenancy_config(self.config,
                                          list(merged.values()))
        self.tenants = {x["id"]: x for x in entries}
        self._restage_tenants()
        if self._rec is not None:
            self._rec.set_tenant_ml(int(tid), ml_mode, ml_thresh)
        self._mark("tenant")

    # --- interfaces ---
    def set_interface(
        self,
        if_index: int,
        if_type: InterfaceType,
        local_table: int = -1,
        apply_global: bool = False,
    ) -> None:
        self.if_type[if_index] = int(if_type)
        self.if_local_table[if_index] = local_table
        self.if_apply_global[if_index] = int(apply_global)
        if self._rec is not None:
            self._rec.set_interface(if_index, int(if_type), local_table,
                                    bool(apply_global))
        self._mark("if")

    def set_if_local_table(self, if_index: int, slot: int) -> None:
        """Point one interface at a local ACL table slot (-1 = none).
        The single mutation point for if_local_table outside
        set_interface — external writers must come through here so the
        'if' upload group gets marked dirty."""
        self.if_local_table[if_index] = slot
        if self._rec is not None:
            self._rec.set_if_local_table(if_index, slot)
        self._mark("if")

    # --- FIB ---
    def _mark_fib_slots(self, *plens: int) -> None:
        """One route mutation: the per-slot row arrays changed (they
        ship via the incremental blob or, fallback, in full) and the
        named prefix LENGTHS need their LPM plane restaged."""
        self._fib_dirty.update(_FIB_SLOT_FIELDS)
        if self.lpm_enabled:
            for plen in plens:
                if 0 <= plen <= 32:
                    self._lpm_dirty_lens.add(int(plen))
        self._mark("fib")

    def add_route(
        self,
        prefix: str,
        tx_if: int,
        disposition: Disposition,
        next_hop: int = 0,
        node_id: int = -1,
        slot: Optional[int] = None,
        snat: bool = False,
        group: Optional[int] = None,
    ) -> int:
        """Install one route. ``group`` names an ECMP next-hop group
        (set_nh_group) the route resolves through instead of the
        scalar next_hop/tx_if/node_id columns — which are still staged
        as given (the trace/debug fallback and the group's fail-closed
        documentation of intent)."""
        net = ipaddress.ip_network(prefix)
        if group is not None:
            gcap = self.fib_grp_nh.shape[0]
            if int(getattr(self.config, "fib_ecmp_groups", 0)) <= 0:
                raise ValueError(
                    "route names an ECMP group but "
                    "dataplane.fib_ecmp_groups is 0")
            if not (0 <= int(group) < gcap):
                raise ValueError(
                    f"ECMP group {group} out of range 0..{gcap - 1}")
        if slot is None:
            free = np.nonzero(self.fib_plen < 0)[0]
            if len(free) == 0:
                raise ValueError("FIB full")
            slot = int(free[0])
        old_plen = int(self.fib_plen[slot])
        mask = _mask_of(net.prefixlen)
        self.fib_prefix[slot] = int(net.network_address) & mask
        self.fib_mask[slot] = mask
        self.fib_plen[slot] = net.prefixlen
        self.fib_tx_if[slot] = tx_if
        self.fib_disp[slot] = int(disposition)
        self.fib_next_hop[slot] = next_hop
        self.fib_node_id[slot] = node_id
        self.fib_snat[slot] = int(snat)
        self.fib_grp[slot] = -1 if group is None else int(group)
        if self._rec is not None:
            self._rec.add_route(prefix, tx_if, int(disposition),
                                int(next_hop), int(node_id), bool(snat),
                                slot=slot, group=group)
        self._mark_fib_slots(old_plen, net.prefixlen)
        return slot

    def add_routes_np(self, nets: np.ndarray, plens: np.ndarray,
                      tx_if: np.ndarray, disp: np.ndarray,
                      next_hop=0, node_id=-1, snat=0, group=-1,
                      base_slot: int = 0) -> int:
        """Bulk route loader (the BGP full-feed path; ISSUE 15):
        vectorized writes of N routes into slots [base_slot,
        base_slot + N). Scalars broadcast; ``nets`` must already be
        masked networks. NOT journaled — a 1M-entry feed is adjacency
        state, not NB config (replay rebuilds it from the feed, the
        way VPP reloads its RIB). Returns the count staged."""
        n = len(nets)
        if base_slot + n > self.config.fib_slots:
            raise ValueError(
                f"{n} routes at base {base_slot} exceed fib_slots "
                f"{self.config.fib_slots}")
        grp = np.asarray(group, np.int32)
        if (grp >= 0).any():
            # the add_route group validation, vectorized: an
            # out-of-range id would be CLIPPED on-device onto a real
            # group and silently forward via its members
            gcap = self.fib_grp_nh.shape[0]
            if int(getattr(self.config, "fib_ecmp_groups", 0)) <= 0:
                raise ValueError(
                    "routes name ECMP groups but "
                    "dataplane.fib_ecmp_groups is 0")
            if int(grp.max()) >= gcap or int(grp.min()) < -1:
                raise ValueError(
                    f"ECMP group ids must be -1 (none) or in "
                    f"0..{gcap - 1}")
        plens = np.asarray(plens, np.int32)
        sl = slice(base_slot, base_slot + n)
        masks = np.array([_mask_of(int(p)) for p in range(33)],
                         np.uint32)[plens]
        old = self.fib_plen[sl]
        self.fib_prefix[sl] = np.asarray(nets, np.uint32) & masks
        self.fib_mask[sl] = masks
        self.fib_plen[sl] = plens
        self.fib_tx_if[sl] = np.asarray(tx_if, np.int32)
        self.fib_disp[sl] = np.asarray(disp, np.int32)
        self.fib_next_hop[sl] = np.asarray(next_hop, np.uint32)
        self.fib_node_id[sl] = np.asarray(node_id, np.int32)
        self.fib_snat[sl] = np.asarray(snat, np.int32)
        self.fib_grp[sl] = np.asarray(group, np.int32)
        touched = set(np.unique(plens).tolist())
        touched |= set(np.unique(old[old >= 0]).tolist())
        self._mark_fib_slots(*touched)
        return n

    def del_route(self, prefix: str) -> bool:
        net = ipaddress.ip_network(prefix)
        mask = _mask_of(net.prefixlen)
        want = int(net.network_address) & mask
        hit = np.nonzero(
            (self.fib_plen == net.prefixlen) & (self.fib_prefix == want)
        )[0]
        if len(hit) == 0:
            return False
        self.fib_plen[hit[0]] = -1
        if self._rec is not None:
            self._rec.del_route(prefix)
        self._mark_fib_slots(net.prefixlen)
        return True

    # --- ECMP next-hop groups (ops/fib.py resolve_fib_slot) ---
    def set_nh_group(self, gid: int, members) -> None:
        """Stage one ECMP group: ``members`` is a sequence of
        ``(next_hop_ip, tx_if, node_id)`` tuples. Way assignment is
        STICKY: surviving members keep the ways they already own (up
        to their rebalanced share), so member churn only remaps the
        flows it must — the stickiness contract tests pin
        (docs/ROUTING.md)."""
        c = self.config
        if int(getattr(c, "fib_ecmp_groups", 0)) <= 0:
            raise ValueError(
                "dataplane.fib_ecmp_groups is 0 — ECMP group tables "
                "carry placeholder shapes (raise the knob)")
        gcap, ways = self.fib_grp_nh.shape
        if not (0 <= int(gid) < gcap):
            raise ValueError(f"ECMP group {gid} out of range "
                             f"0..{gcap - 1}")
        gid = int(gid)
        mset = []
        for m in members:
            nh, tx, node = int(m[0]), int(m[1]), int(m[2])
            if (nh, tx, node) not in mset:
                mset.append((nh, tx, node))
        if not mset:
            raise ValueError(
                "ECMP group needs at least one member "
                "(del_nh_group removes a group)")
        if len(mset) > ways:
            raise ValueError(
                f"{len(mset)} distinct members exceed fib_ecmp_ways "
                f"{ways}")
        prev = self.nh_groups.get(gid)
        prev_assign = list(prev["assign"]) if prev else [None] * ways
        n = len(mset)
        target = [ways // n + (1 if i < ways % n else 0)
                  for i in range(n)]
        counts = [0] * n
        assign_i = [None] * ways
        # pass 1: surviving members keep their ways up to their share
        for w in range(ways):
            m = prev_assign[w]
            if m in mset:
                i = mset.index(m)
                if counts[i] < target[i]:
                    assign_i[w] = i
                    counts[i] += 1
        # pass 2: freed/new ways go to the most under-share member
        # (deterministic: ties by member order)
        for w in range(ways):
            if assign_i[w] is None:
                i = min(range(n), key=lambda j: (counts[j] - target[j], j))
                assign_i[w] = i
                counts[i] += 1
        assign = [mset[i] for i in assign_i]
        self.nh_groups[gid] = {"members": mset, "assign": assign}
        self.fib_grp_nh[gid] = np.array([m[0] for m in assign], np.uint32)
        self.fib_grp_tx_if[gid] = np.array([m[1] for m in assign], np.int32)
        self.fib_grp_node[gid] = np.array([m[2] for m in assign], np.int32)
        self.fib_grp_n[gid] = n
        if self._rec is not None:
            self._rec.set_nh_group(gid, [list(m) for m in mset])
        self._fib_dirty.update(("fib_grp_nh", "fib_grp_tx_if",
                                "fib_grp_node", "fib_grp_n"))
        self._mark("fib")

    def del_nh_group(self, gid: int) -> bool:
        """Remove one ECMP group. Routes still referencing it FAIL
        CLOSED on the device (fib_grp_n == 0 resolves as a no-route
        miss) until they are repointed — dropping beats forwarding to
        a withdrawn next-hop."""
        if int(gid) not in self.nh_groups:
            return False
        gid = int(gid)
        del self.nh_groups[gid]
        self.fib_grp_nh[gid] = 0
        self.fib_grp_tx_if[gid] = -1
        self.fib_grp_node[gid] = -1
        self.fib_grp_n[gid] = 0
        if self._rec is not None:
            self._rec.del_nh_group(gid)
        self._fib_dirty.update(("fib_grp_nh", "fib_grp_tx_if",
                                "fib_grp_node", "fib_grp_n"))
        self._mark("fib")
        return True

    # --- LPM plane staging (ops/lpm.py; ISSUE 15) ---
    def _restage_lpm(self) -> None:
        """Recompile the dirty per-length LPM planes from the per-slot
        FIB arrays: one vectorized pass per dirty length — select that
        length's slots, sort by (prefix, slot), keep the LOWEST slot
        per duplicate prefix (the dense argmax tie-break, so the two
        implementations stay bit-exact). Planes are strictly sorted
        after dedupe (`tools/lint.py --tables` pins it). Called lazily
        from host_arrays()/lpm_ok(); a no-op with nothing dirty."""
        if not self._lpm_dirty_lens or not self.lpm_enabled:
            self._lpm_dirty_lens.clear()
            return
        import time as _t

        from vpp_tpu.ops.lpm import LPM_PAD, lpm_field

        t0 = _t.perf_counter()
        for length in sorted(self._lpm_dirty_lens):
            cap = self.lpm_caps[length]
            slots = np.nonzero(self.fib_plen == length)[0]
            pfx = self.fib_prefix[slots]
            order = np.argsort(pfx, kind="stable")
            pfx, slots = pfx[order], slots[order]
            if len(pfx):
                keep = np.ones(len(pfx), bool)
                keep[1:] = pfx[1:] != pfx[:-1]
                pfx, slots = pfx[keep], slots[keep]
            n = len(pfx)
            self.lpm_counts[length] = n
            field = lpm_field(length)
            plane = np.zeros((2, cap), np.uint32)
            plane[0, :] = LPM_PAD
            nc = min(n, cap)   # overflow => lpm_ok() false, never read
            plane[0, :nc] = pfx[:nc]
            plane[1, :nc] = slots[:nc]
            self.lpm_planes[field] = plane
            self.lpm_cnt[length] = nc
            self._fib_dirty.add(field)
            # stride hint rows of this length (ops/lpm.py): the
            # insertion point of every top-bits bucket boundary, so
            # the device bisection starts inside ONE bucket
            b, off, _steps = self._lpm_layout[length]
            if off >= 0:
                bounds = (np.arange((1 << b) + 1, dtype=np.uint64)
                          << (32 - b))
                self.lpm_hint[off:off + (1 << b) + 1] = np.searchsorted(
                    pfx[:nc], bounds).astype(np.int32)
                self._fib_dirty.add("fib_lpm_hint")
        self._fib_dirty.add("fib_lpm_cnt")
        self._lpm_dirty_lens.clear()
        self.lpm_build_ms = (_t.perf_counter() - t0) * 1e3

    def lpm_ok(self) -> bool:
        """Whether the LPM implementation can serve THIS staged FIB:
        planes allocated, and every populated length fits its
        configured capacity (cap 0 = length not served). False falls
        the selection ladder back to dense — the BV ok=False pattern."""
        if not self.lpm_enabled:
            return False
        self._restage_lpm()
        caps = np.asarray(self.lpm_caps, np.int64)
        return bool((self.lpm_counts <= caps).all())

    def fib_route_count(self) -> int:
        """Live FIB routes staged (the fib_lpm_min_routes ladder input
        and the vpp_tpu_fib_routes gauge)."""
        return int(np.count_nonzero(self.fib_plen >= 0))

    # --- NAT ---
    def set_nat_mapping(
        self,
        slot: int,
        ext_ip: int,
        ext_port: int,
        proto: int,
        backends: Sequence[Tuple[int, int, int]],  # (ip, port, weight)
        boff: int,
        self_snat: bool = False,
    ) -> None:
        """Install a DNAT static mapping with weighted backends at ``slot``,
        placing backends at ``boff`` in the backend arrays."""
        if boff + len(backends) > self.config.nat_backends:
            raise ValueError("NAT backend arrays full")
        cum = 0
        for j, (bip, bport, w) in enumerate(backends):
            cum += w
            self.natb_ip[boff + j] = bip
            self.natb_port[boff + j] = bport
            self.natb_cumw[boff + j] = cum
        self.nat_ext_ip[slot] = ext_ip
        self.nat_ext_port[slot] = ext_port
        self.nat_proto[slot] = proto
        self.nat_boff[slot] = boff
        self.nat_bcnt[slot] = len(backends)
        self.nat_total_w[slot] = cum
        self.nat_self_snat[slot] = int(self_snat)
        if self._rec is not None:
            self._rec.set_nat_mapping(
                slot, int(ext_ip), int(ext_port), int(proto),
                [(int(a), int(b), int(w)) for a, b, w in backends],
                int(boff), bool(self_snat))
        self._mark("nat")

    def clear_nat(self) -> None:
        self.nat_bcnt[:] = 0
        if self._rec is not None:
            self._rec.clear_nat()
        self._mark("nat")

    def set_snat_ip(self, ip: int) -> None:
        """Set the node's SNAT address (0 disables SNAT). The single
        mutation point for ``nat_snat_ip`` — agent bootstrap and the
        service configurator both route through here."""
        self.nat_snat_ip = np.uint32(ip)
        if self._rec is not None:
            self._rec.set_snat_ip(int(ip))
        self._mark("nat")

    # --- VXLAN overlay + service LB (ISSUE 19; docs/OVERLAY.md) ---
    def set_vtep_ip(self, ip: int) -> None:
        """Set the node's local VTEP address (the overlay stage's
        decap admission filter and encap outer source). Rides the tiny
        "config" upload group — a VTEP move ships bytes, not planes."""
        self.ovl_vtep_ip = np.uint32(ip)
        if self._rec is not None:
            self._rec.set_vtep_ip(int(ip))
        self._mark("config")

    def _restage_svc(self) -> None:
        """Compile the service registry into the "svc" upload-group
        arrays. VIP rows are sorted by (ip, port, proto) — the
        --tables invariant — and padding rows stay all-zero with
        bk_n 0, so they can never serve (the half-applied guard: a
        row only matches once its whole backend set is staged).
        Deterministic: the same registry always compiles
        byte-identical arrays (the _restage_tenants discipline)."""
        V, B = svc_capacity(self.config)
        z = np.zeros
        vip_ip = z(V, np.uint32)
        vip_port = z(V, np.int32)
        vip_proto = z(V, np.int32)
        vip_snat = z(V, np.int32)
        bk_n = z(V, np.int32)
        bk_ip = z((V, B), np.uint32)
        bk_port = z((V, B), np.int32)
        for r, key in enumerate(sorted(self.services)):
            e = self.services[key]
            ip, port, proto = key
            vip_ip[r] = ip
            vip_port[r] = port
            vip_proto[r] = proto
            vip_snat[r] = int(e["self_snat"])
            bk_n[r] = len(e["members"])
            bk_ip[r] = np.array([m[0] for m in e["assign"]], np.uint32)
            bk_port[r] = np.array([m[1] for m in e["assign"]], np.int32)
        self.svc = {
            "svc_vip_ip": vip_ip, "svc_vip_port": vip_port,
            "svc_vip_proto": vip_proto, "svc_vip_snat": vip_snat,
            "svc_bk_n": bk_n, "svc_bk_ip": bk_ip,
            "svc_bk_port": bk_port,
        }

    def set_service(self, vip_ip: int, port: int, proto: int,
                    backends: Sequence[Tuple[int, int, int]],
                    self_snat: bool = False) -> None:
        """Stage (or replace) one service VIP's backend set:
        ``backends`` is a sequence of ``(ip, port, weight)`` tuples.
        Way assignment is STICKY per service (the set_nh_group fill,
        weighted by largest remainder): surviving backends keep the
        ways they own up to their rebalanced share, so a rolling
        replacement only remaps the flows it must. Validates
        COMPLETELY before any staging mutates — a refused backend set
        leaves the previous one serving, and a half-applied set can
        never reach the device (the _fold_ml clean-refusal
        contract)."""
        c = self.config
        if int(getattr(c, "svc_vips", 0)) <= 0:
            raise ValueError(
                "dataplane.svc_vips is 0 — the svc planes carry "
                "placeholder shapes (raise the knob)")
        V, B = svc_capacity(c)
        if not (1 <= int(port) <= 65535):
            raise ValueError(
                f"service port must be in 1..65535 (exact match), "
                f"got {port}")
        key = (int(vip_ip) & 0xFFFFFFFF, int(port), int(proto))
        mset = []
        seen = set()
        for m in backends:
            bip, bport, w = int(m[0]), int(m[1]), int(m[2])
            if w <= 0:
                raise ValueError(
                    f"backend weight must be > 0, got {w}")
            if (bip, bport) not in seen:
                seen.add((bip, bport))
                mset.append((bip, bport, w))
        if not mset:
            raise ValueError(
                "service needs at least one backend "
                "(del_service removes a VIP)")
        if len(mset) > B:
            raise ValueError(
                f"{len(mset)} distinct backends exceed "
                f"svc_backend_ways {B}")
        if key not in self.services and len(self.services) >= V:
            raise ValueError(
                f"service table full ({V} VIP rows — raise "
                f"dataplane.svc_vips)")
        prev = self.services.get(key)
        prev_assign = list(prev["assign"]) if prev else [None] * B
        # weighted way targets by largest remainder (deterministic:
        # remainder ties break by member order)
        total_w = sum(m[2] for m in mset)
        raw = [B * m[2] / total_w for m in mset]
        target = [int(r) for r in raw]
        rest = B - sum(target)
        order = sorted(range(len(mset)),
                       key=lambda i: (-(raw[i] - target[i]), i))
        for i in order[:rest]:
            target[i] += 1
        counts = [0] * len(mset)
        assign_i: list = [None] * B
        by_ep = {(m[0], m[1]): i for i, m in enumerate(mset)}
        # pass 1: surviving backends keep their ways up to their share
        # (matched by endpoint, so a weight change alone never evicts)
        for w in range(B):
            pm = prev_assign[w]
            i = by_ep.get((pm[0], pm[1])) if pm is not None else None
            if i is not None and counts[i] < target[i]:
                assign_i[w] = i
                counts[i] += 1
        # pass 2: freed/new ways go to the most under-share backend
        for w in range(B):
            if assign_i[w] is None:
                i = min(range(len(mset)),
                        key=lambda j: (counts[j] - target[j], j))
                assign_i[w] = i
                counts[i] += 1
        assign = [mset[i] for i in assign_i]
        self.services[key] = {"members": mset, "assign": assign,
                              "self_snat": bool(self_snat)}
        self._restage_svc()
        if self._rec is not None:
            self._rec.set_service(key[0], key[1], key[2],
                                  [list(m) for m in mset],
                                  bool(self_snat))
        self._mark("svc")

    def del_service(self, vip_ip: int, port: int, proto: int) -> bool:
        """Remove one service VIP. Flows established to its backends
        keep translating through the NAT-session table until they
        age out; NEW flows to the VIP stop matching immediately."""
        key = (int(vip_ip) & 0xFFFFFFFF, int(port), int(proto))
        if key not in self.services:
            return False
        del self.services[key]
        self._restage_svc()
        if self._rec is not None:
            self._rec.del_service(key[0], key[1], key[2])
        self._mark("svc")
        return True

    def clear_services(self) -> None:
        self.services = {}
        self._restage_svc()
        if self._rec is not None:
            self._rec.clear_services()
        self._mark("svc")

    # staging-state array attributes (everything a mutator can touch,
    # besides the dict-of-arrays acl/glb and the scalars handled
    # explicitly in state_snapshot/state_restore)
    _STATE_ARRAYS = (
        "acl_nrules", "if_type", "if_local_table", "if_apply_global",
        "fib_prefix", "fib_mask", "fib_plen", "fib_tx_if", "fib_disp",
        "fib_next_hop", "fib_node_id", "fib_snat", "fib_grp",
        "fib_grp_nh", "fib_grp_tx_if", "fib_grp_node", "fib_grp_n",
        "lpm_cnt", "lpm_counts", "lpm_hint",
        "nat_ext_ip", "nat_ext_port", "nat_proto", "nat_boff", "nat_bcnt",
        "nat_total_w", "nat_self_snat", "natb_ip", "natb_port",
        "natb_cumw",
    )

    def state_snapshot(self) -> dict:
        """Copy of the whole staged (host) configuration — cheap numpy
        copies, no device state. Pair with state_restore for
        transactional rollback (pipeline/txn.py)."""
        # settle lazy LPM staging first so the snapshot's planes are
        # consistent with its per-slot arrays (restore clears the
        # dirty-length set on that assumption)
        self._restage_lpm()
        return {
            "arrays": {k: getattr(self, k).copy()
                       for k in self._STATE_ARRAYS},
            "acl": {k: v.copy() for k, v in self.acl.items()},
            "acl_bv": {k: v.copy() for k, v in self.acl_bv.items()},
            "acl_bv_ok": self.acl_bv_ok.copy(),
            "glb": {k: v.copy() for k, v in self.glb.items()},
            "glb_nrules": self.glb_nrules,
            "glb_mxu": self.glb_mxu,       # replaced wholesale, never
            "glb_bv": self.glb_bv,         # mutated in place
            "ml": self.ml,                 # replaced wholesale too
            "ml_kind": self.ml_kind,
            "tnt": self.tnt,               # replaced wholesale
            "tenants": {t: dict(e) for t, e in self.tenants.items()},
            "lpm_planes": {k: v.copy()
                           for k, v in self.lpm_planes.items()},
            "nh_groups": {g: {"members": list(e["members"]),
                              "assign": list(e["assign"])}
                          for g, e in self.nh_groups.items()},
            "nat_snat_ip": self.nat_snat_ip,
            "ovl_vtep_ip": self.ovl_vtep_ip,
            "svc": self.svc,               # replaced wholesale
            "services": {k: {"members": list(e["members"]),
                             "assign": list(e["assign"]),
                             "self_snat": e["self_snat"]}
                         for k, e in self.services.items()},
            "dirty": set(self._dirty),
            "rec_ops": list(self._rec.ops) if self._rec is not None else None,
        }

    def state_restore(self, snap: dict) -> None:
        """Restore a state_snapshot (in-place array writes so existing
        references — e.g. cluster builders — stay valid)."""
        for k, v in snap["arrays"].items():
            getattr(self, k)[...] = v
        for k, v in snap["acl"].items():
            self.acl[k][...] = v
        for k, v in snap["acl_bv"].items():
            self.acl_bv[k][...] = v
        self.acl_bv_ok[...] = snap["acl_bv_ok"]
        for k, v in snap["glb"].items():
            self.glb[k][...] = v
        self.glb_nrules = snap["glb_nrules"]
        self.glb_mxu = snap["glb_mxu"]
        self.glb_bv = snap["glb_bv"]
        self.ml = snap["ml"]
        self.ml_kind = snap["ml_kind"]
        self.tnt = snap["tnt"]
        self.tenants = {t: dict(e) for t, e in snap["tenants"].items()}
        for k, v in snap["lpm_planes"].items():
            self.lpm_planes[k][...] = v
        self.nh_groups = {g: {"members": list(e["members"]),
                              "assign": list(e["assign"])}
                          for g, e in snap["nh_groups"].items()}
        # restored planes are content-consistent with the restored
        # per-slot arrays (both came from one snapshot), but the device
        # cache may hold the rolled-back commit — re-ship every fib
        # field conservatively, and force a full per-slot upload
        self._lpm_dirty_lens = set()
        self._fib_dirty = set(_UPLOAD_GROUPS["fib"])
        self._fib_prev = None
        # the identity-diff caches describe the pre-restore rule list;
        # the next set_global_table must full-recompile. The BV device
        # cache may hold planes of the rolled-back commit — every BV
        # field re-uploads conservatively.
        self._glb_rules_ref = None
        self._glb_rows = None
        self._glb_bad = None
        self._bv_cols = None
        self._bv_dirty = set(_UPLOAD_GROUPS["glb_bv"])
        self.nat_snat_ip = snap["nat_snat_ip"]
        self.ovl_vtep_ip = snap["ovl_vtep_ip"]
        self.svc = snap["svc"]
        self.services = {k: {"members": list(e["members"]),
                             "assign": list(e["assign"]),
                             "self_snat": e["self_snat"]}
                         for k, e in snap["services"].items()}
        # the device cache may hold the rolled-back svc commit — force
        # the next upload full (the _fib_prev conservatism)
        self._svc_prev = None
        # union, not replace: groups the rolled-back ops touched stay
        # dirty — a redundant re-upload of identical data is harmless,
        # a stale device cache is not
        self._dirty |= set(snap["dirty"])
        if self._rec is not None and snap.get("rec_ops") is not None:
            self._rec.ops[:] = snap["rec_ops"]

    # --- device upload ---
    def host_arrays(self) -> Dict[str, np.ndarray]:
        """The staged configuration as numpy arrays keyed by
        DataplaneTables field name (everything except session state).
        Used directly by to_device() and, node-stacked, by the cluster
        data plane (vpp_tpu.parallel.cluster). Settles the lazy LPM
        plane staging first (dirty lengths recompile here, once)."""
        self._restage_lpm()
        return dict(
            acl_src_net=self.acl["src_net"],
            acl_src_mask=self.acl["src_mask"],
            acl_dst_net=self.acl["dst_net"],
            acl_dst_mask=self.acl["dst_mask"],
            acl_proto=self.acl["proto"],
            acl_sport_lo=self.acl["sport_lo"],
            acl_sport_hi=self.acl["sport_hi"],
            acl_dport_lo=self.acl["dport_lo"],
            acl_dport_hi=self.acl["dport_hi"],
            acl_action=self.acl["action"],
            acl_nrules=self.acl_nrules,
            acl_bv_bnd_src=self.acl_bv["bnd_src"],
            acl_bv_bnd_dst=self.acl_bv["bnd_dst"],
            acl_bv_bnd_sport=self.acl_bv["bnd_sport"],
            acl_bv_bnd_dport=self.acl_bv["bnd_dport"],
            acl_bv_nbnd=self.acl_bv["nbnd"],
            acl_bv_src=self.acl_bv["src"],
            acl_bv_dst=self.acl_bv["dst"],
            acl_bv_sport=self.acl_bv["sport"],
            acl_bv_dport=self.acl_bv["dport"],
            acl_bv_proto=self.acl_bv["proto"],
            glb_src_net=self.glb["src_net"],
            glb_src_mask=self.glb["src_mask"],
            glb_dst_net=self.glb["dst_net"],
            glb_dst_mask=self.glb["dst_mask"],
            glb_proto=self.glb["proto"],
            glb_sport_lo=self.glb["sport_lo"],
            glb_sport_hi=self.glb["sport_hi"],
            glb_dport_lo=self.glb["dport_lo"],
            glb_dport_hi=self.glb["dport_hi"],
            glb_action=self.glb["action"],
            glb_nrules=np.int32(self.glb_nrules),
            glb_mxu_coeff=self.glb_mxu.coeff,
            glb_mxu_k=self.glb_mxu.k,
            glb_mxu_act=self.glb_mxu.act,
            glb_bv_bnd_src=self.glb_bv.bnd_src,
            glb_bv_bnd_dst=self.glb_bv.bnd_dst,
            glb_bv_bnd_sport=self.glb_bv.bnd_sport,
            glb_bv_bnd_dport=self.glb_bv.bnd_dport,
            glb_bv_nbnd=self.glb_bv.nbnd,
            glb_bv_src=self.glb_bv.bm_src,
            glb_bv_dst=self.glb_bv.bm_dst,
            glb_bv_sport=self.glb_bv.bm_sport,
            glb_bv_dport=self.glb_bv.bm_dport,
            glb_bv_proto=self.glb_bv.bm_proto,
            **self.ml,
            **self.tnt,
            if_type=self.if_type,
            if_local_table=self.if_local_table,
            if_apply_global=self.if_apply_global,
            fib_prefix=self.fib_prefix,
            fib_mask=self.fib_mask,
            fib_plen=self.fib_plen,
            fib_tx_if=self.fib_tx_if,
            fib_disp=self.fib_disp,
            fib_next_hop=self.fib_next_hop,
            fib_node_id=self.fib_node_id,
            fib_snat=self.fib_snat,
            fib_grp=self.fib_grp,
            **self.lpm_planes,
            fib_lpm_cnt=self.lpm_cnt,
            fib_lpm_hint=self.lpm_hint,
            fib_grp_nh=self.fib_grp_nh,
            fib_grp_tx_if=self.fib_grp_tx_if,
            fib_grp_node=self.fib_grp_node,
            fib_grp_n=self.fib_grp_n,
            sess_max_age=np.int32(self.config.sess_max_age),
            nat_ext_ip=self.nat_ext_ip,
            nat_ext_port=self.nat_ext_port,
            nat_proto=self.nat_proto,
            nat_boff=self.nat_boff,
            nat_bcnt=self.nat_bcnt,
            nat_total_w=self.nat_total_w,
            nat_self_snat=self.nat_self_snat,
            natb_ip=self.natb_ip,
            natb_port=self.natb_port,
            natb_cumw=self.natb_cumw,
            nat_snat_ip=self.nat_snat_ip,
            ovl_vtep_ip=self.ovl_vtep_ip,
            **self.svc,
        )

    def to_device(self, sessions=None) -> DataplaneTables:
        """Produce the immutable device pytree. If ``sessions`` (a previous
        epoch's tables) is given, its live session arrays are carried over.

        ``sessions`` may also be a ``{field: host array}`` mapping of
        SESSION_FIELDS (the crash-consistent snapshot restore path,
        pipeline/snapshot.py): the arrays are uploaded and a restarted
        agent's established flows come back warm. Shapes must match the
        config geometry — the snapshot loader already refused a
        geometry mismatch, so a bad shape here is a programming error
        and raises.

        Incremental: only fields of groups mutated since the previous
        call are re-uploaded; clean groups reuse the cached device
        arrays (each upload is a host→device transfer — a full RPC
        round trip on remote transports — and the bit-plane matrix
        alone is several MB at 10k rules). Do NOT donate a tables
        pytree produced here into a jit (donate_argnums) if you will
        swap again afterwards: donation invalidates the cached buffers
        the next swap would reuse."""
        if isinstance(sessions, dict):
            missing = set(SESSION_FIELDS) - set(sessions)
            if missing:
                raise ValueError(
                    f"restored session state missing fields: "
                    f"{sorted(missing)}")
            shapes = session_shapes(self.config)
            for f, arr in sessions.items():
                if tuple(np.shape(arr)) != shapes[f]:
                    raise ValueError(
                        f"restored session field {f!r} shape "
                        f"{tuple(np.shape(arr))} != configured "
                        f"{shapes[f]}")
            sess = {f: jnp.asarray(np.asarray(sessions[f], dt))
                    for f, dt in SESSION_FIELDS.items()}
            # telemetry + tenancy state restart cold on a snapshot
            # restore by design: the snapshot format carries
            # SESSION_FIELDS only, and measurement state from before a
            # crash would mislabel the post-restart regime (the token
            # buckets refill within one step)
            tel = zero_telemetry_device(self.config)
            tnt_st = zero_tenancy_state_device(self.config)
            fib_st = zero_fib_state_device(self.config)
        elif sessions is not None:
            # carry-over is BY REFERENCE: the live device arrays flow
            # into the new epoch untouched — at 10M slots the session
            # state is ~100s of MB and must never re-ship on a swap.
            # The telemetry planes (ops/telemetry.py) and the tenancy
            # state (token buckets + accounting planes, ISSUE 14) ride
            # the same carry: an epoch swap must not reset them.
            sess = {f: getattr(sessions, f) for f in SESSION_FIELDS}
            tel = {f: getattr(sessions, f) for f in TELEMETRY_FIELDS}
            tnt_st = {f: getattr(sessions, f)
                      for f in TENANCY_STATE_FIELDS}
            fib_st = {f: getattr(sessions, f) for f in FIB_STATE_FIELDS}
        else:
            # device-side zero fill, not a host upload of zeros
            sess = zero_sessions_device(self.config)
            tel = zero_telemetry_device(self.config)
            tnt_st = zero_tenancy_state_device(self.config)
            fib_st = zero_fib_state_device(self.config)
        host_np = self.host_arrays()
        host = {}
        glb_full = False
        self.fib_last_shipped = False
        for group, fields in _UPLOAD_GROUPS.items():
            dirty = group in self._dirty
            if group == "fib":
                self._upload_fib(host, host_np, fields, dirty)
                continue
            if group == "svc":
                self._upload_svc(host, host_np, fields, dirty)
                continue
            if group == "glb_bv":
                # per-dimension-plane upload: only planes compile_bv
                # rebuilt since the last to_device re-ship (a port-only
                # churn keeps the multi-MB address bitmaps cached);
                # a field with no cache entry always uploads
                for name in fields:
                    if (dirty and name in self._bv_dirty) \
                            or name not in self._dev_cache:
                        self._dev_cache[name] = jnp.asarray(host_np[name])
                    host[name] = self._dev_cache[name]
                self._bv_dirty.clear()
                continue
            if group == "glb" and dirty:
                if self._glb_incremental(host_np):
                    # changed row/column BLOCKS were scattered into the
                    # cached device arrays with one blob upload — the
                    # multi-MB full-table re-upload (415 ms on the r3
                    # tunnel at 10k rules) is skipped (VERDICT r3
                    # Next #6)
                    dirty = False
                else:
                    glb_full = True
            for name in fields:
                if dirty or name not in self._dev_cache:
                    self._dev_cache[name] = jnp.asarray(host_np[name])
                host[name] = self._dev_cache[name]
        if glb_full:
            # diff base refreshed only AFTER the full upload above
            # completed — refreshing before a device call that then
            # fails would desync the base and make a retried commit
            # no-op while the device serves stale rules
            self._set_glb_prev(host_np)
        self._dirty.clear()
        return DataplaneTables(**host, **sess, **tel, **tnt_st,
                               **fib_st)

    def _set_glb_prev(self, host_np: Dict[str, np.ndarray]) -> None:
        """Record the diff base for incremental glb commits. The ROW
        arrays are COPIED: state_restore writes into the live glb
        arrays in place, so a reference would alias the base with
        whatever a later rollback restores and a subsequent diff would
        see 'no change' against content the device never received. The
        bit-plane arrays are safe references (set_global_table and
        state_restore both replace the MxuTable wholesale)."""
        prev = {f: host_np[f].copy() for f in _GLB_ROW_FIELDS}
        for f in ("glb_mxu_coeff", "glb_mxu_k", "glb_mxu_act",
                  "glb_nrules"):
            prev[f] = host_np[f]
        self._glb_prev = prev

    def _glb_incremental(self, host_np: Dict[str, np.ndarray]) -> bool:
        """Try an incremental device update of the global-table group:
        diff against the last-uploaded host arrays, and when the
        changes confine to a block, upload ONE packed blob and scatter
        it into the cached device arrays (see _glb_update_fn). Returns
        True when the device cache now holds the new epoch (the caller
        skips the full re-upload); False falls back to full upload. The
        diff base refreshes ONLY on success — on the False path the
        caller must refresh it after the full upload completes
        (to_device does), so a failed device call never desyncs it."""
        from vpp_tpu.ops.acl_mxu import PLANES

        prev = self._glb_prev
        if prev is None or any(
            f not in self._dev_cache for f in _UPLOAD_GROUPS["glb"]
        ):
            return False
        n_rows = host_np["glb_action"].shape[0]
        n_cols = host_np["glb_mxu_k"].shape[0]
        changed_r = np.zeros(n_rows, bool)
        for f in _GLB_ROW_FIELDS:
            changed_r |= prev[f] != host_np[f]
        changed_c = (prev["glb_mxu_k"] != host_np["glb_mxu_k"]) \
            | (prev["glb_mxu_act"] != host_np["glb_mxu_act"]) \
            | np.any(prev["glb_mxu_coeff"] != host_np["glb_mxu_coeff"],
                     axis=0)
        blk_r = _block_of(changed_r, n_rows)
        blk_c = _block_of(changed_c, n_cols)
        if blk_r is None and blk_c is None:
            # content-identical commit (e.g. rolled-back txn): only the
            # rule-count scalar may differ
            if int(prev["glb_nrules"]) != int(host_np["glb_nrules"]):
                self._dev_cache["glb_nrules"] = jnp.asarray(
                    host_np["glb_nrules"]
                )
            self._set_glb_prev(host_np)
            return True
        blk_r = blk_r or (0, min(256, n_rows))
        blk_c = blk_c or (0, min(256, n_cols))
        lo_r, w_r = blk_r
        lo_c, w_c = blk_c
        if w_r >= n_rows or w_c >= n_cols:
            return False  # change spans the table: full upload is best
        blob = np.empty(10 * w_r + 2 * w_c + PLANES * w_c, np.int32)
        for i, f in enumerate(_GLB_ROW_FIELDS):
            blob[i * w_r:(i + 1) * w_r] = \
                host_np[f][lo_r:lo_r + w_r].view(np.int32)
        base = 10 * w_r
        blob[base:base + w_c] = \
            host_np["glb_mxu_k"][lo_c:lo_c + w_c].view(np.int32)
        blob[base + w_c:base + 2 * w_c] = \
            host_np["glb_mxu_act"][lo_c:lo_c + w_c]
        blob[base + 2 * w_c:] = np.ascontiguousarray(
            host_np["glb_mxu_coeff"][:, lo_c:lo_c + w_c]
        ).reshape(-1).view(np.int32)
        fn = _glb_update_fn(w_r, w_c, PLANES)
        new_rows, new_k, new_act, new_coeff = fn(
            [self._dev_cache[f] for f in _GLB_ROW_FIELDS],
            self._dev_cache["glb_mxu_k"],
            self._dev_cache["glb_mxu_act"],
            self._dev_cache["glb_mxu_coeff"],
            jnp.asarray(blob), lo_r, lo_c,
        )
        for f, arr in zip(_GLB_ROW_FIELDS, new_rows):
            self._dev_cache[f] = arr
        self._dev_cache["glb_mxu_k"] = new_k
        self._dev_cache["glb_mxu_act"] = new_act
        self._dev_cache["glb_mxu_coeff"] = new_coeff
        self._dev_cache["glb_nrules"] = jnp.asarray(host_np["glb_nrules"])
        # base refreshed only now — after every device call succeeded
        self._set_glb_prev(host_np)
        return True

    # --- FIB upload (per-length planes + incremental slot blob) ---
    def _upload_fib(self, host: Dict[str, object],
                    host_np: Dict[str, np.ndarray],
                    fields: Tuple[str, ...], dirty: bool) -> None:
        """The "fib" group's to_device body (ISSUE 15): per-slot row
        arrays go through the incremental scatter-blob path when the
        commit's changes confine to a block (a route flap ships a
        few-KB blob, not 9 full columns); the per-length LPM planes
        and ECMP tables re-ship only when ``_fib_dirty`` names them —
        every other plane keeps its cached device-array identity.
        Records ``fib_upload`` for `show fib` / fib_bench."""
        import time as _t

        t0 = _t.perf_counter()
        shipped = []
        blob_bytes = 0
        slot_inc = False
        if dirty:
            blob_bytes = self._fib_incremental(host_np)
            slot_inc = blob_bytes is not None
        for name in fields:
            if name in _FIB_SLOT_FIELDS and slot_inc:
                # the blob already scattered this field's block into
                # the cached device array
                host[name] = self._dev_cache[name]
                continue
            if (dirty and name in self._fib_dirty) \
                    or name not in self._dev_cache:
                self._dev_cache[name] = jnp.asarray(host_np[name])
                shipped.append(name)
            host[name] = self._dev_cache[name]
        if dirty and not slot_inc:
            # full per-slot upload above: refresh the diff base only
            # after every device transfer succeeded (the glb rule)
            self._set_fib_prev(host_np)
        if dirty:
            self.fib_last_shipped = True
            self.fib_upload = {
                "fields": tuple(shipped),
                "blob_bytes": int(blob_bytes or 0),
                "bytes": int(sum(host_np[f].nbytes for f in shipped)
                             + (blob_bytes or 0)),
                "ms": (_t.perf_counter() - t0) * 1e3,
            }
            self._fib_dirty.clear()

    def _set_fib_prev(self, host_np: Dict[str, np.ndarray]) -> None:
        """Record the per-slot diff base (COPIES — state_restore
        writes the live arrays in place, the _set_glb_prev rationale)."""
        self._fib_prev = {f: host_np[f].copy() for f in _FIB_SLOT_FIELDS}

    def _fib_incremental(self, host_np: Dict[str, np.ndarray]):
        """Try an incremental device update of the per-slot FIB rows:
        diff against the last-uploaded arrays; when the changes
        confine to a block, upload ONE packed blob and scatter it into
        the cached device arrays (_fib_update_fn). Returns the blob's
        byte count on success (0 = content-identical commit), None to
        fall back to a full upload. The diff base refreshes only on
        success — a failed device call never desyncs it."""
        prev = self._fib_prev
        if prev is None or any(
            f not in self._dev_cache for f in _FIB_SLOT_FIELDS
        ):
            return None
        n = host_np["fib_plen"].shape[0]
        changed = np.zeros(n, bool)
        for f in _FIB_SLOT_FIELDS:
            changed |= prev[f] != host_np[f]
        blk = _block_of(changed, n)
        if blk is None:
            return 0   # content-identical commit: nothing to ship
        lo, w = blk
        if w >= n:
            return None  # change spans the table: full upload is best
        nf = len(_FIB_SLOT_FIELDS)
        blob = np.empty(nf * w, np.int32)
        for i, f in enumerate(_FIB_SLOT_FIELDS):
            blob[i * w:(i + 1) * w] = host_np[f][lo:lo + w].view(np.int32)
        fn = _fib_update_fn(w)
        new_rows = fn([self._dev_cache[f] for f in _FIB_SLOT_FIELDS],
                      jnp.asarray(blob), lo)
        for f, arr in zip(_FIB_SLOT_FIELDS, new_rows):
            self._dev_cache[f] = arr
        self._set_fib_prev(host_np)
        return blob.nbytes

    # --- service-plane upload (incremental VIP-row blob; ISSUE 19) --
    def _upload_svc(self, host: Dict[str, object],
                    host_np: Dict[str, np.ndarray],
                    fields: Tuple[str, ...], dirty: bool) -> None:
        """The "svc" group's to_device body (the _upload_fib twin):
        changed VIP rows go through the incremental scatter-blob path
        when they confine to a block — a rolling backend replacement
        ships a few-KB blob, never the full planes, and NEVER any
        other group's bytes. Records ``svc_upload`` for
        `show services` / overlay_bench's svc_churn_bytes."""
        import time as _t

        t0 = _t.perf_counter()
        shipped = []
        blob_bytes = 0
        inc = False
        if dirty:
            blob_bytes = self._svc_incremental(host_np)
            inc = blob_bytes is not None
        for name in fields:
            if inc:
                host[name] = self._dev_cache[name]
                continue
            if dirty or name not in self._dev_cache:
                self._dev_cache[name] = jnp.asarray(host_np[name])
                shipped.append(name)
            host[name] = self._dev_cache[name]
        if dirty and not inc:
            # full upload above: refresh the diff base only after
            # every device transfer succeeded (the glb/fib rule)
            self._set_svc_prev(host_np)
        if dirty:
            self.svc_last_shipped = True
            self.svc_upload = {
                "fields": tuple(shipped),
                "blob_bytes": int(blob_bytes or 0),
                "bytes": int(sum(host_np[f].nbytes for f in shipped)
                             + (blob_bytes or 0)),
                "ms": (_t.perf_counter() - t0) * 1e3,
            }

    def _set_svc_prev(self, host_np: Dict[str, np.ndarray]) -> None:
        """Record the svc diff base (safe references — _restage_svc
        replaces the staging arrays wholesale, never in place)."""
        self._svc_prev = {f: host_np[f]
                          for f in _SVC_1D_FIELDS + _SVC_2D_FIELDS}

    def _svc_incremental(self, host_np: Dict[str, np.ndarray]):
        """Try an incremental device update of the service planes:
        diff VIP rows against the last-uploaded arrays; when the
        changes confine to a row block, upload ONE packed blob and
        scatter it into the cached device arrays (_svc_update_fn).
        Returns the blob's byte count on success (0 =
        content-identical commit), None to fall back to a full
        upload."""
        prev = self._svc_prev
        all_fields = _SVC_1D_FIELDS + _SVC_2D_FIELDS
        if prev is None or any(
            f not in self._dev_cache for f in all_fields
        ):
            return None
        V, B = host_np["svc_bk_ip"].shape
        changed = np.zeros(V, bool)
        for f in _SVC_1D_FIELDS:
            changed |= prev[f] != host_np[f]
        for f in _SVC_2D_FIELDS:
            changed |= np.any(prev[f] != host_np[f], axis=1)
        idx = np.nonzero(changed)[0]
        if len(idx) == 0:
            return 0   # content-identical commit: nothing to ship
        # _block_of's 256-row floor suits rule/FIB tables; VIP tables
        # are small, so the blob ladder starts at 8 rows (x4 steps)
        lo, hi = int(idx[0]), int(idx[-1]) + 1
        w = 8
        while w < hi - lo:
            w *= 4
        if w >= V:
            return None  # change spans the table: full upload is best
        lo = min(lo, V - w)
        n1 = len(_SVC_1D_FIELDS)
        blob = np.empty(n1 * w + len(_SVC_2D_FIELDS) * w * B, np.int32)
        for i, f in enumerate(_SVC_1D_FIELDS):
            blob[i * w:(i + 1) * w] = host_np[f][lo:lo + w].view(np.int32)
        base = n1 * w
        for i, f in enumerate(_SVC_2D_FIELDS):
            blob[base + i * w * B:base + (i + 1) * w * B] = \
                np.ascontiguousarray(
                    host_np[f][lo:lo + w]).reshape(-1).view(np.int32)
        fn = _svc_update_fn(w, B)
        new_rows, new_grids = fn(
            [self._dev_cache[f] for f in _SVC_1D_FIELDS],
            [self._dev_cache[f] for f in _SVC_2D_FIELDS],
            jnp.asarray(blob), lo,
        )
        for f, arr in zip(_SVC_1D_FIELDS, new_rows):
            self._dev_cache[f] = arr
        for f, arr in zip(_SVC_2D_FIELDS, new_grids):
            self._dev_cache[f] = arr
        self._set_svc_prev(host_np)
        return blob.nbytes
