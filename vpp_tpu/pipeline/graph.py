"""The fused packet pipeline: one jitted step over a packet vector.

Reference analog: the VPP graph-node chain installed by the agent
(SURVEY.md §3.5): ip4-input → acl-plugin-fa → nat44 → ip4-lookup →
[vxlan/remote] → interface-tx. VPP dispatches frames node-to-node through
a scheduler; under XLA the whole chain is traced once and fused, with
tables passed in functionally so a renderer commit is an epoch swap.

Counters follow VPP's per-node/per-interface model and feed the
statscollector (Prometheus) equivalent.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from vpp_tpu.ops.acl import acl_classify_global, acl_classify_local
from vpp_tpu.ops.fib import fib_lookup_dense
from vpp_tpu.ops.ip4 import ip4_input
from vpp_tpu.ops.nat44 import (
    nat44_dnat,
    nat44_dnat_match,
    nat44_record,
    nat44_reverse,
    nat44_snat,
    nat44_touch,
)
from vpp_tpu.ops.mlscore import ml_policy, ml_score
from vpp_tpu.ops.session import (
    session_batch_summary,
    session_hit_age,
    session_insert,
    session_lookup_reverse_idx,
    session_sweep,
    session_touch,
)
from vpp_tpu.pipeline.tables import DataplaneTables
from vpp_tpu.pipeline.vector import Disposition, PacketVector


class StepStats(NamedTuple):
    """Per-step counters (VPP `show errors` / interface counters analog)."""

    rx: jnp.ndarray            # int32 scalar: valid packets processed
    tx: jnp.ndarray            # int32 scalar: packets forwarded
    drop_ip4: jnp.ndarray      # int32 scalar: ip4-input drops (TTL/len)
    drop_acl: jnp.ndarray      # int32 scalar: policy denies
    drop_no_route: jnp.ndarray  # int32 scalar: FIB misses
    punt: jnp.ndarray          # int32 scalar: packets punted to host stack
    dnat: jnp.ndarray          # int32 scalar: DNAT translations applied
    snat: jnp.ndarray          # int32 scalar: SNAT translations applied
    nat_reversed: jnp.ndarray  # int32 scalar: reply-path un-NAT hits
    drop_nat: jnp.ndarray      # int32 scalar: NAT fail-closed drops
                               # (SNAT port collision / un-NATable proto
                               # on an SNAT egress route)
    sess_insert_fail: jnp.ndarray     # int32 scalar: reflective-session
                                      # probe-window congestion (no slot)
    natsess_insert_fail: jnp.ndarray  # int32 scalar: NAT-session insert
                                      # congestion
    sess_occupancy: jnp.ndarray       # int32 scalar: live reflective slots
    natsess_occupancy: jnp.ndarray    # int32 scalar: live NAT slots
    if_rx: jnp.ndarray         # int32 [I] per-interface rx packets
    if_tx: jnp.ndarray         # int32 [I] per-interface tx packets
    if_rx_bytes: jnp.ndarray   # int32 [I]
    if_tx_bytes: jnp.ndarray   # int32 [I]
    if_drops: jnp.ndarray      # int32 [I] drops attributed to the rx if
    sess_hits: jnp.ndarray     # int32 scalar: alive packets admitted via
                               # a live reflective session (the fast-path
                               # dispatch signal, two-tier pipeline)
    fastpath: jnp.ndarray      # int32 scalar: 1 when this step ran the
                               # classify-free established-flow kernel,
                               # 0 for the full chain
    # set-associative insert reclamation (ops/session.py): ways
    # reclaimed by an insert, split by reason — ``expired`` is the
    # benign idle-timeout reclaim, ``victim`` means a FULL bucket
    # evicted its oldest live session to admit a new flow (the true
    # table-pressure signal)
    sess_evict_expired: jnp.ndarray     # int32 scalar
    sess_evict_victim: jnp.ndarray      # int32 scalar
    natsess_evict_expired: jnp.ndarray  # int32 scalar
    natsess_evict_victim: jnp.ndarray   # int32 scalar
    # per-packet ML scoring stage (ops/mlscore.py; all 0 with the
    # stage compiled off): alive packets scored, packets whose score
    # crossed the model's flag threshold, and packets the ENFORCE
    # policy actually dropped (score mode never drops)
    ml_scored: jnp.ndarray              # int32 scalar
    ml_flagged: jnp.ndarray             # int32 scalar
    ml_drops: jnp.ndarray               # int32 scalar
    # device-resident telemetry plane (ops/telemetry.py; 0 below
    # telemetry "full"): alive packets folded into the count-min
    # heavy-hitter flow sketch this step
    tel_sketched: jnp.ndarray           # int32 scalar
    # multi-tenant gateway mode (vpp_tpu/tenancy/; both 0 with the
    # tenancy stage compiled off): packets dropped by a tenant's
    # token-bucket rate limit this step (attributed DROP_TENANT →
    # drops_total{reason="tenant_quota"}), and session/NAT inserts
    # that failed inside a tenant's capacity slice (the per-tenant
    # congestion signal — a full slice contends only with itself)
    tnt_limited: jnp.ndarray            # int32 scalar
    tnt_qfail: jnp.ndarray              # int32 scalar
    # device-resident VXLAN overlay stage pair (ISSUE 19; all 0 with
    # ``overlay: off`` — the stage compiles out): frames decapped at
    # ip4-input (inner vector re-admitted in place), frames encapped
    # at tx (outer header built on-device, resolved by the second FIB
    # walk), and overlay-ADDRESSED frames that failed validation
    # (unknown/absent VNI, invalid inner framing — fail closed,
    # attributed DROP_OVERLAY)
    ovl_decap: jnp.ndarray              # int32 scalar
    ovl_encap: jnp.ndarray              # int32 scalar
    drop_overlay: jnp.ndarray           # int32 scalar


# Per-packet drop attribution (error-drop counter analog). Values must
# stay < 16: the packed IO boundary carries the cause in a nibble
# (pipeline/dataplane.py _packed_call output row 3).
DROP_NONE = 0
DROP_IP4 = 1        # ip4-input: TTL/length/bad interface
DROP_ACL = 2        # policy deny
DROP_NO_ROUTE = 3   # FIB miss
DROP_FIB = 4        # matched a drop route
DROP_NAT = 5        # NAT fail-closed (port collision / un-NATable proto)
DROP_ML = 6         # ML-stage enforce verdict (drop / rate-limited)
DROP_TENANT = 7     # tenant token-bucket quota exceeded (ISSUE 14)
DROP_OVERLAY = 8    # overlay fail-closed: VXLAN-addressed frame with a
                    # bad/unknown VNI or invalid inner framing (ISSUE 19)

DROP_CAUSE_NAMES = {
    DROP_NONE: "none",
    DROP_IP4: "ip4-input",
    DROP_ACL: "acl-deny",
    DROP_NO_ROUTE: "no-route",
    DROP_FIB: "fib-drop",
    DROP_NAT: "nat-drop",
    DROP_ML: "ml-drop",
    DROP_TENANT: "tenant-quota",
    DROP_OVERLAY: "overlay-drop",
}


class StepResult(NamedTuple):
    pkts: PacketVector         # header fields after rewrites (TTL, NAT)
    disp: jnp.ndarray          # int32 [P] Disposition per packet
    tx_if: jnp.ndarray         # int32 [P] egress interface (-1 if dropped)
    node_id: jnp.ndarray       # int32 [P] destination node (-1 local)
    next_hop: jnp.ndarray      # uint32 [P] peer IP for remote disposition
    tables: DataplaneTables    # tables with updated session state
    stats: StepStats
    drop_cause: jnp.ndarray    # int32 [P] DROP_* attribution (0 = none)
    established: jnp.ndarray   # bool [P] admitted via reflective session
    dnat_applied: jnp.ndarray  # bool [P] DNAT rewrote the destination
    snat_applied: jnp.ndarray  # bool [P] SNAT rewrote the source
    ml_flagged: jnp.ndarray    # bool [P] ML score crossed the flag
                               # threshold (the mirror mask: the IO
                               # path can copy these out; all-False
                               # with the stage off)
    ml_scores: jnp.ndarray     # int32 [P] raw per-packet ML scores
                               # (the PacketTracer's ml-score node
                               # reads them; all-zero with the stage
                               # off — packed paths never fetch them)
    # overlay stage pair outputs (ISSUE 19) — None with ``overlay:
    # off`` (the gate is trace-time static, so both lax.cond tiers of
    # the auto dispatcher agree on the pytree structure). ``ovl_outer``
    # is the on-device-built outer header vector (valid exactly where
    # ``ovl_encap``); the host IO edge serializes (outer, inner, vni)
    # via ops/vxlan.encode_frame — no io_callback on the wire path.
    ovl_outer: Optional[PacketVector] = None
    ovl_encap: Optional[jnp.ndarray] = None   # bool [P] encapped at tx
    ovl_vni: Optional[jnp.ndarray] = None     # int32 [P] wire VNI
                                              # (-1 where not encapped)


def _ingress(tables: DataplaneTables, pkts: PacketVector):
    """Shared ingress prologue of every pipeline tier: ip4-input plus
    the unconfigured-interface drop (VPP analog: unknown sw_if_index →
    error-drop). One copy, so an ingress-semantics change lands on the
    full chain, the fast kernel and the dispatch predicate alike.
    Returns (pkts, drop_ip4, alive)."""
    pkts, drop_ip4 = ip4_input(pkts)
    bad_if = tables.if_type[pkts.rx_if] == 0
    drop_ip4 = drop_ip4 | (bad_if & pkts.valid)
    return pkts, drop_ip4, pkts.valid & ~drop_ip4


def _tenant_eval(tables: DataplaneTables, pkts: PacketVector,
                 alive: jnp.ndarray, now, tnt_mode: str,
                 ovl_tid=None, ovl_decapped=None):
    """The ONE copy of the tenant stage's stateful half (ISSUE 14),
    run EXACTLY ONCE per fused step (both pipeline tiers, and the
    two-tier dispatcher runs it ahead of the branch and hands the
    result to whichever tier wins — tokens are consumed once either
    way): derive each packet's tenant id on the ingress header
    (tenancy/derive.py — symmetric max of the src/dst prefix matches)
    and run the per-tenant token bucket. Returns ``(tid, dropped,
    tables')`` — ``tid`` is None with the stage compiled off (every
    consumer then takes its pre-tenancy path, and the zero ``dropped``
    constant folds away).

    With the overlay stage on (ISSUE 19), ``ovl_tid``/``ovl_decapped``
    carry the decap stage's VNI-named tenant: a decapped packet's
    tenant IS its VNI's tenant (the on-device VNI ↔ tenant pact,
    docs/OVERLAY.md) and the address derivation is overridden for
    exactly those lanes — underlay addresses say nothing about the
    inner flow's tenant."""
    # jax-ok: tnt_mode is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if tnt_mode == "off":
        return None, jnp.zeros(alive.shape, bool), tables
    from vpp_tpu.tenancy.derive import tenant_ids, tenant_limit

    tid = tenant_ids(tables, pkts)
    # jax-ok: ovl_tid None-ness is trace-time static (the overlay gate
    # decides it at step-factory time), not a tracer branch
    if ovl_tid is not None:
        tid = jnp.where(ovl_decapped, ovl_tid, tid)
    tables, dropped = tenant_limit(tables, tid, alive, now)
    return tid, dropped, tables


def _ml_eval(tables: DataplaneTables, pkts: PacketVector,
             alive: jnp.ndarray, established: jnp.ndarray,
             sess_age: jnp.ndarray, ml_mode: str, ml_kind: str,
             shard=None, tid=None):
    """The ONE copy of the ML-stage evaluation (ISSUE 10), shared by
    the full chain and the established-flow fast tier so the two can
    never silently diverge: scored on the post-NAT-reverse header plus
    the reflective-session hit state/age — values both tiers hold at
    their scoring point, bit-identically.

    Returns ``(scored, flagged, drop_wanted, scores)`` — three masks
    [P] plus the raw int32 score vector (the PacketTracer's ml-score
    node renders it; zeros with the stage off). ``ml_mode`` /
    ``ml_kind`` are trace-time-static step-factory gates: "off"
    returns all-False constants XLA folds away (the stage costs
    nothing when disabled); "score" never requests drops; only
    "enforce" passes the policy's drop verdict through — which the
    pipeline then applies AFTER the ACL verdict (deny beats ml-drop
    beats permit, pinned by tests/test_ml_stage.py)."""
    # jax-ok: ml_mode/ml_kind are trace-time-static step-factory gates
    # (Python strings baked into the jit key), not tracer branches
    if ml_mode == "off":
        false_p = jnp.zeros(alive.shape, bool)
        return false_p, false_p, false_p, jnp.zeros(alive.shape,
                                                    jnp.int32)
    scores = ml_score(tables, pkts, established, sess_age, kind=ml_kind,
                      shard=shard)
    flagged, drop_wanted = ml_policy(tables, pkts, alive, scores,
                                     tid=tid)
    # jax-ok: ml_mode is the same trace-time-static gate as above —
    # score mode statically discards the policy's drop verdict
    if ml_mode != "enforce":
        drop_wanted = jnp.zeros(alive.shape, bool)
    return alive, flagged, drop_wanted, scores


def _finish_step(
    tables: DataplaneTables,
    pkts: PacketVector,
    now: jnp.ndarray,
    alive: jnp.ndarray,
    drop_ip4: jnp.ndarray,
    drop_acl: jnp.ndarray,
    permit: jnp.ndarray,
    fib,
    forwarded: jnp.ndarray,
    disp: jnp.ndarray,
    tx_if: jnp.ndarray,
    established: jnp.ndarray,
    nat_reversed: jnp.ndarray,
    dnat_applied: jnp.ndarray,
    snat_applied: jnp.ndarray,
    dropped_nat: jnp.ndarray,
    sess_fail: jnp.ndarray,
    natsess_fail: jnp.ndarray,
    fastpath: jnp.ndarray,
    sess_evict_expired: jnp.ndarray,
    sess_evict_victim: jnp.ndarray,
    natsess_evict_expired: jnp.ndarray,
    natsess_evict_victim: jnp.ndarray,
    ml_scored: jnp.ndarray,
    ml_flagged: jnp.ndarray,
    ml_dropped: jnp.ndarray,
    ml_scores: jnp.ndarray,
    sweep_stride: int = 0,
    tel_mode: str = "off",
    shard=None,
    tnt_mode: str = "off",
    tid=None,
    tnt_dropped=None,
    tnt_qfail=None,
    overlay: str = "off",
    fib_fn=fib_lookup_dense,
    ovl_dropped=None,
    ovl_decapped=None,
) -> StepResult:
    """Shared tail of both pipeline tiers: drop attribution, counters,
    StepStats and the StepResult assembly. The ONE copy of the
    accounting semantics — the fast kernel calls it with its statically
    empty NAT/insert masks (all-False vectors, which XLA folds), so an
    edit to drop_cause/occupancy/per-interface logic lands on both
    tiers by construction. Also the ONE place the amortized session
    sweep runs (``sweep_stride`` buckets per table per step —
    ops/session.py session_sweep), so aging rides EVERY tier of the
    fused program identically — and the ONE place the heavy-hitter
    flow sketch (ops/telemetry.py; ``tel_mode`` "full", trace-time
    static) folds the batch in, so both tiers feed the same sketch.
    With ``overlay: vxlan`` (ISSUE 19) it is also the ONE place the
    encap half of the overlay stage pair runs — both tiers build the
    outer header and resolve it through the second FIB walk here."""
    if ovl_dropped is None:
        ovl_dropped = jnp.zeros(alive.shape, bool)
    if ovl_decapped is None:
        ovl_decapped = jnp.zeros(alive.shape, bool)
    # --- overlay encap at tx (ISSUE 19): REMOTE-disposed packets with
    # a tunnel next_hop get an on-device outer header (entropy sport
    # from the inner 5-tuple — ops/vxlan.vxlan_encap) resolved by a
    # SECOND walk over the SAME fib planes: the inner walk's ECMP
    # group already spread tunnel endpoints on the flow hash
    # (next_hop IS the chosen VTEP), the outer walk routes TO that
    # endpoint. An unroutable endpoint folds into drop_no_route, fail
    # closed. The outer walk is deliberately NOT fed into the
    # per-member ECMP accounting below — the inner walk already
    # attributed this packet to its group member; counting the
    # outer-route group too would double-bill the plane.
    # jax-ok: overlay is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if overlay != "off":
        from vpp_tpu.ops.vxlan import DEFAULT_VNI, vxlan_encap

        ovl_need = (forwarded & (disp == int(Disposition.REMOTE))
                    & (fib.next_hop != 0))
        ovl_outer = vxlan_encap(pkts, ovl_need, tables.ovl_vtep_ip,
                                fib.next_hop)
        ofib = fib_fn(tables, ovl_outer)
        ofib_ok = ofib.matched & (ofib.disp != int(Disposition.DROP))
        ovl_miss = ovl_need & ~ofib_ok
        forwarded = forwarded & ~ovl_miss
        disp = jnp.where(ovl_miss, int(Disposition.DROP),
                         disp).astype(jnp.int32)
        ovl_encap = ovl_need & ofib_ok
        tx_if = jnp.where(ovl_encap, ofib.tx_if,
                          jnp.where(ovl_miss, -1, tx_if))
        ovl_outer = ovl_outer._replace(
            flags=jnp.where(ovl_encap, ovl_outer.flags, 0))
        # per-tenant VNI on the wire: the tenant's configured VNI
        # (tnt_vni — tenancy off keeps slot 0 at DEFAULT_VNI), with
        # DEFAULT_VNI covering tenants that configured none
        # jax-ok: tid None-ness is the trace-time-static tnt gate
        if tid is not None:
            vni_raw = tables.tnt_vni[tid]
        else:
            vni_raw = jnp.broadcast_to(tables.tnt_vni[0], alive.shape)
        vni = jnp.where(vni_raw >= 0, vni_raw, DEFAULT_VNI)
        ovl_vni_out = jnp.where(ovl_encap, vni, -1).astype(jnp.int32)
    else:
        ovl_miss = jnp.zeros(alive.shape, bool)
        ovl_encap = jnp.zeros(alive.shape, bool)
        ovl_outer = None
        ovl_vni_out = None
    tables = session_sweep(tables, now, sweep_stride)
    # per-member ECMP accounting (ISSUE 15; ops/fib.py resolve): one
    # flat scatter-add of forwarded group-routed packets into the
    # carried [G, W] plane — both tiers feed it here, the one place.
    # Non-ECMP packets (grp -1) target the out-of-range index and drop.
    n_grp, n_way = tables.fib_ecmp_c.shape
    gw = jnp.where(forwarded & (fib.grp >= 0),
                   fib.grp * n_way + fib.way, n_grp * n_way)
    tables = tables._replace(
        fib_ecmp_c=tables.fib_ecmp_c.reshape(-1).at[gw].add(
            1, mode="drop").reshape(n_grp, n_way))
    # jax-ok: tel_mode is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if tel_mode == "full":
        from vpp_tpu.ops.telemetry import tel_flow_update

        tables, tel_sketched = tel_flow_update(tables, pkts, alive)
    else:
        tel_sketched = jnp.int32(0)
    # tenancy masks (ISSUE 14): ``alive`` at this point EXCLUDES
    # rate-limited packets (both tiers mask right after the tenant
    # stage); alive_all restores them for the rx/per-interface counts
    # — they were real received traffic, dropped with attribution
    if tnt_dropped is None:
        tnt_dropped = jnp.zeros(alive.shape, bool)
    if tnt_qfail is None:
        tnt_qfail = jnp.zeros(alive.shape, bool)
    # overlay fail-closed lanes left ``alive`` right after ip4-input
    # (the decap stage's bad mask) but were real received traffic —
    # alive_all restores them for rx/per-interface counts exactly
    # like the rate-limited lanes
    alive_all = alive | tnt_dropped | ovl_dropped
    # jax-ok: tnt_mode is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if tnt_mode != "off":
        from vpp_tpu.tenancy.derive import tnt_account

        tables = tnt_account(tables, tid, alive_all, forwarded,
                             tnt_dropped, tnt_qfail)
    n_ifaces = tables.if_type.shape[0]

    def occupancy(valid, time):
        """Live slots (valid, not idle-expired). Sharded, the local
        sum covers this shard's bucket range; one psum makes the
        scalar the whole table's occupancy on every shard — StepStats
        outputs must be replicated along the rule axis."""
        occ = jnp.sum(((valid == 1)
                       & (now - time <= tables.sess_max_age)
                       ).astype(jnp.int32))
        if shard is not None:
            from jax import lax

            occ = lax.psum(occ, shard.axis)
        return occ

    # ml-drop wins attribution over the FIB outcomes (the packet never
    # reached forwarding), but LOSES to ACL deny: ml_dropped is
    # already masked to permitted traffic by the callers
    drop_no_route = (alive & permit & ~fib.matched & ~ml_dropped
                     | ovl_miss)
    fib_dropped = alive & permit & fib.matched & (
        fib.disp == int(Disposition.DROP)
    ) & ~ml_dropped
    dropped = (
        (pkts.valid & (drop_ip4 | drop_acl | drop_no_route))
        | fib_dropped
        | dropped_nat
        | ml_dropped
        | tnt_dropped
        | ovl_dropped
    )
    rx_if_safe = jnp.where(alive_all, pkts.rx_if, n_ifaces)
    tx_if_safe = jnp.where(forwarded, tx_if, n_ifaces)
    drop_if_safe = jnp.where(dropped, pkts.rx_if, n_ifaces)
    zero_i = jnp.zeros((n_ifaces,), jnp.int32)
    stats = StepStats(
        rx=jnp.sum(alive_all.astype(jnp.int32)),
        tx=jnp.sum(forwarded.astype(jnp.int32)),
        drop_ip4=jnp.sum(drop_ip4.astype(jnp.int32)),
        drop_acl=jnp.sum(drop_acl.astype(jnp.int32)),
        drop_no_route=jnp.sum(drop_no_route.astype(jnp.int32)),
        punt=jnp.sum(
            (forwarded & (disp == int(Disposition.HOST))).astype(jnp.int32)
        ),
        dnat=jnp.sum((dnat_applied & forwarded).astype(jnp.int32)),
        snat=jnp.sum((snat_applied & forwarded).astype(jnp.int32)),
        nat_reversed=jnp.sum((nat_reversed & forwarded).astype(jnp.int32)),
        drop_nat=jnp.sum(dropped_nat.astype(jnp.int32)),
        sess_insert_fail=jnp.sum(sess_fail.astype(jnp.int32)),
        natsess_insert_fail=jnp.sum(natsess_fail.astype(jnp.int32)),
        # live = valid and not idle-expired (what lookups actually see)
        sess_occupancy=occupancy(tables.sess_valid, tables.sess_time),
        natsess_occupancy=occupancy(tables.natsess_valid,
                                    tables.natsess_time),
        if_rx=zero_i.at[rx_if_safe].add(1, mode="drop"),
        if_tx=zero_i.at[tx_if_safe].add(1, mode="drop"),
        if_rx_bytes=zero_i.at[rx_if_safe].add(
            jnp.where(alive_all, pkts.pkt_len, 0), mode="drop"
        ),
        if_tx_bytes=zero_i.at[tx_if_safe].add(
            jnp.where(forwarded, pkts.pkt_len, 0), mode="drop"
        ),
        if_drops=zero_i.at[drop_if_safe].add(1, mode="drop"),
        sess_hits=jnp.sum(established.astype(jnp.int32)),
        fastpath=fastpath,
        sess_evict_expired=jnp.sum(sess_evict_expired.astype(jnp.int32)),
        sess_evict_victim=jnp.sum(sess_evict_victim.astype(jnp.int32)),
        natsess_evict_expired=jnp.sum(
            natsess_evict_expired.astype(jnp.int32)),
        natsess_evict_victim=jnp.sum(
            natsess_evict_victim.astype(jnp.int32)),
        ml_scored=jnp.sum(ml_scored.astype(jnp.int32)),
        ml_flagged=jnp.sum(ml_flagged.astype(jnp.int32)),
        ml_drops=jnp.sum(ml_dropped.astype(jnp.int32)),
        tel_sketched=tel_sketched,
        tnt_limited=jnp.sum(tnt_dropped.astype(jnp.int32)),
        tnt_qfail=jnp.sum(tnt_qfail.astype(jnp.int32)),
        ovl_decap=jnp.sum(ovl_decapped.astype(jnp.int32)),
        ovl_encap=jnp.sum(ovl_encap.astype(jnp.int32)),
        drop_overlay=jnp.sum(ovl_dropped.astype(jnp.int32)),
    )
    # attribution stays exclusive: tnt_dropped packets left ``alive``
    # right after the tenant stage, so every other cause mask (all
    # derived from alive/permit/forwarded) excludes them; ovl_dropped
    # lanes likewise left right after ip4-input (and exclude the
    # drop_ip4 lanes — the decap stage masks them out)
    drop_cause = (
        jnp.where(pkts.valid & drop_ip4, DROP_IP4, 0)
        + jnp.where(drop_acl, DROP_ACL, 0)
        + jnp.where(drop_no_route, DROP_NO_ROUTE, 0)
        + jnp.where(fib_dropped, DROP_FIB, 0)
        + jnp.where(dropped_nat, DROP_NAT, 0)
        + jnp.where(ml_dropped, DROP_ML, 0)
        + jnp.where(tnt_dropped, DROP_TENANT, 0)
        + jnp.where(ovl_dropped, DROP_OVERLAY, 0)
    ).astype(jnp.int32)
    return StepResult(
        pkts=pkts,
        disp=disp,
        tx_if=tx_if,
        node_id=jnp.where(forwarded, fib.node_id, -1),
        next_hop=jnp.where(forwarded, fib.next_hop, jnp.uint32(0)),
        tables=tables,
        stats=stats,
        drop_cause=drop_cause,
        established=established,
        dnat_applied=dnat_applied,
        snat_applied=snat_applied,
        ml_flagged=ml_flagged,
        ml_scores=ml_scores,
        ovl_outer=ovl_outer,
        ovl_encap=ovl_encap if overlay != "off" else None,
        ovl_vni=ovl_vni_out,
    )



# Buckets swept per table per fused step when the caller doesn't plumb
# the DataplaneConfig knob (the cluster step, module-level jits, tests
# calling pipeline_step directly).
SWEEP_STRIDE_DEFAULT = 256


def pipeline_step(
    tables: DataplaneTables,
    pkts: PacketVector,
    now: jnp.ndarray,
    acl_global_fn=acl_classify_global,
    acl_local_fn=acl_classify_local,
    sweep_stride: int = SWEEP_STRIDE_DEFAULT,
    ml_mode: str = "off",
    ml_kind: str = "mlp",
    tel_mode: str = "off",
    tnt_mode: str = "off",
    fib_fn=fib_lookup_dense,
    sess_impl: str = "gather",
    sess_hash: str = "fwd",
    shard=None,
    overlay: str = "off",
    ovl_inner=None,
    ovl_vni=None,
    _tnt_pre=None,
) -> StepResult:
    """Process one packet vector through the full forwarding chain.

    Pure function: (tables, frame, time) → (result, new session state).
    Jit once; call per frame. ``acl_global_fn`` lets the multi-chip
    cluster step substitute a rule-sharded global classify
    (vpp_tpu.parallel.cluster) without altering the chain;
    ``acl_local_fn`` swaps the per-interface classify the same way
    (the BV implementation, or the policy-free skip —
    ``make_pipeline_step`` composes both). ``sweep_stride`` buckets per
    session table are aged inside the step (trace-time static —
    ops/session.py session_sweep). ``ml_mode``/``ml_kind`` gate the
    per-packet ML scoring stage (trace-time static — ``_ml_eval``).
    ``shard`` (parallel/partition.py ShardCtx) marks the session/NAT
    bucket grids and ML weight planes as rule-axis shards: the session
    ops hash globally and recombine with psums, so the chain's
    per-packet results stay bit-exact vs standalone (docs/PARTITIONING.md).

    ``overlay: vxlan`` (ISSUE 19) engages the fused overlay stage
    pair: decap runs HERE, ahead of ip4-input (the outer header plus
    the host-parsed ``ovl_inner``/``ovl_vni`` sidecar — the inner
    vector is re-admitted in place, fail-closed lanes leave ``alive``
    attributed DROP_OVERLAY), and encap runs at tx inside the shared
    tail. Trace-time static like every other gate — ONE step-form
    dimension in the jit cache, zero io_callbacks.
    """
    # --- overlay decap at ip4-input (ISSUE 19) ---
    # jax-ok: overlay is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if overlay != "off":
        from vpp_tpu.ops.vxlan import vxlan_decap_step

        pkts, ovl_bad, ovl_decapped, ovl_tid = vxlan_decap_step(
            tables, pkts, ovl_inner, ovl_vni)
    else:
        ovl_bad = ovl_decapped = ovl_tid = None

    # --- ip4-input (+ unconfigured-interface drop) ---
    pkts, drop_ip4, alive = _ingress(tables, pkts)
    # jax-ok: same trace-time-static overlay gate as above
    if overlay != "off":
        # fail-closed overlay lanes leave here; ip4-input keeps
        # attribution priority on lanes it already dropped (the outer
        # header must parse before the decap verdict means anything)
        ovl_dropped = ovl_bad & ~drop_ip4
        alive = alive & ~ovl_dropped
    else:
        ovl_dropped = None

    # --- tenant stage (ISSUE 14): derive + token-bucket ONCE per step.
    # ``_tnt_pre`` is the two-tier dispatcher's pre-consumed trio (it
    # runs _tenant_eval ahead of the lax.cond so neither branch
    # double-consumes tokens); rate-limited packets leave ``alive``
    # here — no session touch, no NAT state, no forwarding, attributed
    # DROP_TENANT in the shared tail.
    # jax-ok: _tnt_pre None-ness is trace-time static (the dispatcher
    # always passes it under tenancy), not a tracer branch
    if _tnt_pre is not None:
        tid, tnt_dropped, tables = _tnt_pre
    else:
        tid, tnt_dropped, tables = _tenant_eval(tables, pkts, alive,
                                                now, tnt_mode,
                                                ovl_tid=ovl_tid,
                                                ovl_decapped=ovl_decapped)
    alive = alive & ~tnt_dropped
    tnt = tnt_mode != "off"

    # --- reflective session bypass (return traffic of permitted flows) ---
    # Looked up on the raw (pre-NAT) header: forward sessions are installed
    # post-DNAT, so a backend's reply B→C reverses to the stored C→B key.
    # Expired entries (idle > sess_max_age ticks) don't match, and hits
    # refresh the timestamp — active flows never expire mid-flow.
    established, sess_hit_idx = session_lookup_reverse_idx(
        tables, pkts, now, shard=shard, tnt=tnt, impl=sess_impl,
        sym=sess_hash == "sym")
    established = established & alive
    # pre-touch session age: an ML feature (the touch below refreshes
    # the timestamp, so the age must be captured first — the fast tier
    # captures it at the same pre-touch point, docs/ML_STAGE.md)
    sess_age = session_hit_age(tables, sess_hit_idx, established, now,
                               shard=shard)
    tables = session_touch(tables, sess_hit_idx, established, now,
                           shard=shard)

    # --- NAT44: reverse-translate return traffic, then DNAT new flows ---
    pkts, nat_reversed, nat_hit_idx = nat44_reverse(tables, pkts, alive,
                                                    now, shard=shard,
                                                    tnt=tnt)
    tables = nat44_touch(tables, nat_hit_idx, nat_reversed, now,
                         shard=shard)

    # --- per-packet ML scoring (ISSUE 10): on the post-reverse header,
    # the same values the fast tier scores — ONE shared evaluation
    ml_scored, ml_flagged, ml_drop_want, ml_scores = _ml_eval(
        tables, pkts, alive, established, sess_age, ml_mode, ml_kind,
        shard=shard, tid=tid)

    orig_dst, orig_dport = pkts.dst_ip, pkts.dport
    pkts, dnat_applied, dnat_self_snat = nat44_dnat(
        tables, pkts, alive & ~nat_reversed
    )

    # --- ACL classify (local per-interface table + node-global table) ---
    local_v = acl_local_fn(tables, pkts)
    glob_v = acl_global_fn(tables, pkts)
    permit = (local_v.permit & glob_v.permit) | established
    drop_acl = alive & ~permit

    # enforce-mode ML verdict, folded AFTER the ACL verdict: an
    # ACL-denied packet stays an ACL drop (deny beats ml-drop), an
    # ACL-permitted flagged packet drops here (ml-drop beats permit)
    ml_dropped = ml_drop_want & permit & alive

    # --- ip4-lookup (on possibly NAT-rewritten dst; dense or LPM per
    # the fib_impl ladder — both resolve through ops.fib) ---
    fib = fib_fn(tables, pkts)
    forwarded = (alive & permit & ~ml_dropped & fib.matched
                 & (fib.disp != int(Disposition.DROP)))
    disp = jnp.where(forwarded, fib.disp, int(Disposition.DROP)).astype(jnp.int32)
    tx_if = jnp.where(forwarded, fib.tx_if, -1)

    # --- SNAT for cluster-egress flows (routes marked snat) and for
    # self-snat DNAT mappings (nodeports: the backend's reply must return
    # through this node for un-DNAT even when the backend is remote).
    # New outbound flows only: reply traffic (un-NAT'd above, or admitted
    # via a reflective session) must keep its translated/original source.
    # Reference: configurator_impl.go:258-264 SNAT pool.
    is_l4 = (pkts.proto == 6) | (pkts.proto == 17)
    nat_capable = is_l4 | (pkts.proto == 1)  # icmp: src-only translation
    fresh = ~nat_reversed & ~established
    orig_src, orig_sport = pkts.src_ip, pkts.sport
    want_snat = forwarded & fresh & nat_capable & (fib.snat | dnat_self_snat)
    pkts, snat_applied = nat44_snat(tables, pkts, want_snat)
    # A protocol NAT can't translate, leaving via an SNAT route, would
    # leak the pod's private source address — fail closed.
    nat_unsupported = (
        forwarded & fresh & ~nat_capable & fib.snat
        & (tables.nat_snat_ip != 0)
    )

    # --- session install for newly permitted flows only (denied packets
    # must not consume session slots); keys are post-NAT so replies match ---
    want_sess = forwarded & ~established & nat_capable & ~nat_unsupported
    tables, _, sess_fail, sess_ev_exp, sess_ev_vic = session_insert(
        tables, pkts, want_sess, now, shard=shard, tnt=tnt,
        sym=sess_hash == "sym")
    nat_kind = (
        jnp.where(dnat_applied, 1, 0) + jnp.where(snat_applied, 2, 0)
    ).astype(jnp.int32)
    tables, nat_conflict, natsess_fail, nat_ev_exp, nat_ev_vic = nat44_record(
        tables, pkts, orig_dst, orig_dport, orig_src, orig_sport, nat_kind,
        (dnat_applied | snat_applied) & forwarded, now, shard=shard,
        tnt=tnt,
    )
    # Fail closed on reply-key collisions (two SNAT'd flows hashed onto
    # the same external port): misdelivering replies to the wrong pod is
    # worse than dropping the colliding flow — drops are counted.
    dropped_nat = nat_conflict | nat_unsupported
    forwarded = forwarded & ~dropped_nat
    disp = jnp.where(dropped_nat, int(Disposition.DROP), disp).astype(jnp.int32)
    tx_if = jnp.where(dropped_nat, -1, tx_if)

    # counters / attribution / result assembly: the shared tail
    return _finish_step(
        tables, pkts, now, alive, drop_ip4, drop_acl, permit, fib,
        forwarded, disp, tx_if, established, nat_reversed, dnat_applied,
        snat_applied, dropped_nat, sess_fail, natsess_fail,
        fastpath=jnp.int32(0),
        sess_evict_expired=sess_ev_exp, sess_evict_victim=sess_ev_vic,
        natsess_evict_expired=nat_ev_exp, natsess_evict_victim=nat_ev_vic,
        ml_scored=ml_scored, ml_flagged=ml_flagged, ml_dropped=ml_dropped,
        ml_scores=ml_scores, sweep_stride=sweep_stride, tel_mode=tel_mode,
        shard=shard, tnt_mode=tnt_mode, tid=tid, tnt_dropped=tnt_dropped,
        # only meaningful with the stage on (the per-tenant congestion
        # signal); the off-state constant keeps the counter at 0
        tnt_qfail=(sess_fail | natsess_fail) if tnt else None,
        overlay=overlay, fib_fn=fib_fn, ovl_dropped=ovl_dropped,
        ovl_decapped=ovl_decapped,
    )


# --- two-tier established-flow fast path ------------------------------
#
# BENCH_r05 put 15.4 ms of the 24.2 ms fused step in the global ACL
# classify, yet steady-state traffic is return flows the reflective
# session table already admits — the full chain computed `established`
# and then ran the classifier anyway just to OR the verdicts. The split
# below is the VPP acl-plugin flow-cache idea on a vector machine:
# per-PACKET branching is impossible under XLA (every lane executes
# every instruction), so the dispatch granularity is the BATCH — one
# `lax.cond` on "every valid packet hit a live session (and none
# touches DNAT state)" picks a classify-free kernel for the whole
# vector, and any partial-hit batch falls through to the full chain
# bit-for-bit unchanged.


def _pipeline_fast_finish(
    tables: DataplaneTables,
    pkts: PacketVector,
    now: jnp.ndarray,
    alive: jnp.ndarray,
    drop_ip4: jnp.ndarray,
    established: jnp.ndarray,
    sess_hit_idx: jnp.ndarray,
    nat_reversed: jnp.ndarray,
    nat_hit_idx: jnp.ndarray,
    sweep_stride: int = SWEEP_STRIDE_DEFAULT,
    ml_mode: str = "off",
    ml_kind: str = "mlp",
    tel_mode: str = "off",
    tnt_mode: str = "off",
    fib_fn=fib_lookup_dense,
    shard=None,
    tid=None,
    tnt_dropped=None,
    overlay: str = "off",
    ovl_dropped=None,
    ovl_decapped=None,
) -> StepResult:
    """Tail of the classify-free kernel, from post-reverse headers on.

    Valid ONLY under the dispatch invariant (every alive packet is
    established, none DNAT-matches): `permit` collapses to
    `established`, SNAT/session-insert/NAT-record are statically empty
    (they all require a fresh flow or a DNAT hit) and are elided rather
    than computed-and-discarded — that elision IS the speedup.

    The ML stage is NOT elided: the fast tier still scores (and in
    enforce mode still drops) every packet — anomaly traffic rides
    established flows too, and a fast tier that skipped the model
    would silently diverge from the full chain exactly on the
    steady-state traffic the model exists to police. ``_ml_eval`` is
    the ONE shared evaluation; the age feature is captured pre-touch
    here exactly as the full chain captures it.
    """
    # tenancy (ISSUE 14): ``alive``/``established`` arrive POST-limit
    # from the callers (the tenant stage ran before the lookups, the
    # full-chain order); tid/tnt_dropped ride through to the shared
    # tail for attribution + per-tenant accounting
    if tnt_dropped is None:
        tnt_dropped = jnp.zeros(alive.shape, bool)
    # pre-touch session age (the ML age feature — full-chain parity)
    sess_age = session_hit_age(tables, sess_hit_idx, established, now,
                               shard=shard)
    tables = session_touch(tables, sess_hit_idx, established, now,
                           shard=shard)
    tables = nat44_touch(tables, nat_hit_idx, nat_reversed, now,
                         shard=shard)

    # permit == (local & glob) | established on every alive packet by
    # the dispatch invariant, so the classify is skipped outright
    permit = established
    drop_acl = alive & ~permit

    ml_scored, ml_flagged, ml_drop_want, ml_scores = _ml_eval(
        tables, pkts, alive, established, sess_age, ml_mode, ml_kind,
        shard=shard, tid=tid)
    ml_dropped = ml_drop_want & permit & alive

    fib = fib_fn(tables, pkts)
    forwarded = alive & permit & ~ml_dropped & fib.matched & (
        fib.disp != int(Disposition.DROP)
    )
    disp = jnp.where(forwarded, fib.disp, int(Disposition.DROP)).astype(
        jnp.int32
    )
    tx_if = jnp.where(forwarded, fib.tx_if, -1)

    # the elided stages are statically empty under the invariant: hand
    # the shared tail all-False masks (XLA folds the dead reductions)
    false_p = jnp.zeros(alive.shape, bool)
    return _finish_step(
        tables, pkts, now, alive, drop_ip4, drop_acl, permit, fib,
        forwarded, disp, tx_if, established, nat_reversed,
        dnat_applied=false_p, snat_applied=false_p, dropped_nat=false_p,
        sess_fail=false_p, natsess_fail=false_p, fastpath=jnp.int32(1),
        sess_evict_expired=false_p, sess_evict_victim=false_p,
        natsess_evict_expired=false_p, natsess_evict_victim=false_p,
        ml_scored=ml_scored, ml_flagged=ml_flagged, ml_dropped=ml_dropped,
        ml_scores=ml_scores, sweep_stride=sweep_stride, tel_mode=tel_mode,
        shard=shard, tnt_mode=tnt_mode, tid=tid, tnt_dropped=tnt_dropped,
        # the fast tier inserts nothing, so slice quota failures are
        # statically empty here (the all-False constant XLA folds)
        tnt_qfail=None,
        overlay=overlay, fib_fn=fib_fn, ovl_dropped=ovl_dropped,
        ovl_decapped=ovl_decapped,
    )


def pipeline_step_fast(
    tables: DataplaneTables, pkts: PacketVector, now: jnp.ndarray,
    sweep_stride: int = SWEEP_STRIDE_DEFAULT,
    ml_mode: str = "off",
    ml_kind: str = "mlp",
    tel_mode: str = "off",
    tnt_mode: str = "off",
    fib_fn=fib_lookup_dense,
    sess_impl: str = "gather",
    sess_hash: str = "fwd",
    shard=None,
    overlay: str = "off",
    ovl_inner=None,
    ovl_vni=None,
) -> StepResult:
    """The classify-free established-flow kernel, standalone:
    [overlay decap] → ip4-input → session lookup/touch → NAT
    reverse/touch → [ML score] → FIB → tx [→ overlay encap].

    Bit-exact with ``pipeline_step`` ONLY when every valid packet hits
    a live reflective session and none DNAT-matches — the invariant
    ``pipeline_step_auto``'s dispatch predicate guarantees. Exposed on
    its own for the differential test and the bench's speedup capture;
    production traffic goes through the auto dispatcher.
    """
    # jax-ok: overlay is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if overlay != "off":
        from vpp_tpu.ops.vxlan import vxlan_decap_step

        pkts, ovl_bad, ovl_decapped, ovl_tid = vxlan_decap_step(
            tables, pkts, ovl_inner, ovl_vni)
    else:
        ovl_bad = ovl_decapped = ovl_tid = None
    pkts, drop_ip4, alive = _ingress(tables, pkts)
    # jax-ok: same trace-time-static overlay gate as above
    if overlay != "off":
        ovl_dropped = ovl_bad & ~drop_ip4
        alive = alive & ~ovl_dropped
    else:
        ovl_dropped = None
    # tenant stage first — the full-chain order, so the two tiers stay
    # bit-exact under the dispatch invariant with tenancy on too
    tid, tnt_dropped, tables = _tenant_eval(tables, pkts, alive, now,
                                            tnt_mode, ovl_tid=ovl_tid,
                                            ovl_decapped=ovl_decapped)
    alive = alive & ~tnt_dropped
    tnt = tnt_mode != "off"
    established, sess_hit_idx = session_lookup_reverse_idx(
        tables, pkts, now, shard=shard, tnt=tnt, impl=sess_impl,
        sym=sess_hash == "sym")
    established = established & alive
    pkts, nat_reversed, nat_hit_idx = nat44_reverse(tables, pkts, alive,
                                                    now, shard=shard,
                                                    tnt=tnt)
    return _pipeline_fast_finish(
        tables, pkts, now, alive, drop_ip4, established, sess_hit_idx,
        nat_reversed, nat_hit_idx, sweep_stride=sweep_stride,
        ml_mode=ml_mode, ml_kind=ml_kind, tel_mode=tel_mode,
        tnt_mode=tnt_mode, fib_fn=fib_fn, shard=shard, tid=tid,
        tnt_dropped=tnt_dropped, overlay=overlay,
        ovl_dropped=ovl_dropped, ovl_decapped=ovl_decapped,
    )


def pipeline_step_auto(
    tables: DataplaneTables,
    pkts: PacketVector,
    now: jnp.ndarray,
    acl_global_fn=acl_classify_global,
    acl_local_fn=acl_classify_local,
    sweep_stride: int = SWEEP_STRIDE_DEFAULT,
    ml_mode: str = "off",
    ml_kind: str = "mlp",
    tel_mode: str = "off",
    tnt_mode: str = "off",
    fib_fn=fib_lookup_dense,
    sess_impl: str = "gather",
    sess_hash: str = "fwd",
    shard=None,
    overlay: str = "off",
    ovl_inner=None,
    ovl_vni=None,
) -> StepResult:
    """Two-tier dispatch: the fast kernel when the whole batch rides
    established sessions, the full chain otherwise.

    With the overlay on (ISSUE 19) the decap stage runs ahead of the
    predicate — established INNER flows ride the fast tier even when
    they arrive encapped, which is exactly the east-west steady state
    the tier exists for. The full branch re-derives from the pre-decap
    vector (identical by construction, like the ingress masks).

    With tenancy on (ISSUE 14) the tenant stage runs HERE, ahead of
    the branch: token consumption is stateful and must happen exactly
    once per step, so the dispatcher consumes and hands the trio to
    whichever tier wins (the full branch takes it via ``_tnt_pre``
    instead of re-running ``_tenant_eval``). The dispatch predicate
    evaluates on the post-limit alive set — a rate-limited packet
    skips every downstream stage identically in both tiers.

    The predicate work (ip4-input, session summary, NAT reverse, DNAT
    probe) is computed once up front; the fast branch reuses it via
    closure, the full branch recomputes inside ``pipeline_step`` —
    paying a second session/NAT lookup only on the path that is about
    to pay the full classifier anyway. ``lax.cond`` executes exactly
    one branch per batch, so steady-state (all-established) traffic
    never touches the ACL tables.

    The predicate additionally requires that NO packet would DNAT-match
    after un-NAT: a reflective-session hit whose destination is also a
    service VIP still takes the full chain, because the full chain
    DNATs it and records NAT state the fast kernel elides.

    SPMD-uniformity under the mesh (``shard``): the sharded session
    summary already recombines per-shard hits with a psum, and the
    dispatch flag is additionally ALL-REDUCED (``pmin`` of each shard's
    flag) before the ``lax.cond`` — every shard provably takes the
    same branch, so the collectives inside both tiers line up. This is
    what lets the fast tier finally run under shard_map (the pre-ISSUE-
    12 cluster pump documented the predicate as not SPMD-uniform and
    pinned the mesh to the full chain).
    """
    from jax import lax

    orig_pkts = pkts
    # jax-ok: overlay is a trace-time-static step-factory gate (a
    # Python string baked into the jit key), not a tracer branch
    if overlay != "off":
        from vpp_tpu.ops.vxlan import vxlan_decap_step

        pkts, ovl_bad, ovl_decapped, ovl_tid = vxlan_decap_step(
            tables, pkts, ovl_inner, ovl_vni)
    else:
        ovl_bad = ovl_decapped = ovl_tid = None
    pkts1, drop_ip4, alive = _ingress(tables, pkts)
    # jax-ok: same trace-time-static overlay gate as above
    if overlay != "off":
        ovl_dropped = ovl_bad & ~drop_ip4
        alive = alive & ~ovl_dropped
    else:
        ovl_dropped = None
    # tenant stage ONCE, ahead of the branch (docstring); tbl carries
    # the consumed token buckets into whichever tier wins
    tid, tnt_dropped, tbl = _tenant_eval(tables, pkts1, alive, now,
                                         tnt_mode, ovl_tid=ovl_tid,
                                         ovl_decapped=ovl_decapped)
    alive = alive & ~tnt_dropped
    tnt = tnt_mode != "off"
    hits, sess_hit_idx, all_hit = session_batch_summary(
        tbl, pkts1, alive, now, shard=shard, tnt=tnt, impl=sess_impl,
        sym=sess_hash == "sym"
    )
    # NAT reverse runs before the DNAT probe: the un-NAT'd header is
    # what the full chain would hand nat44_dnat
    rpkts, nat_reversed, nat_hit_idx = nat44_reverse(
        tbl, pkts1, alive, now, shard=shard, tnt=tnt
    )
    dnat_would = nat44_dnat_match(tbl, rpkts, alive & ~nat_reversed)
    ok = all_hit & ~jnp.any(dnat_would)
    if shard is not None:
        # the all-reduce that makes the dispatch provably uniform: the
        # inputs are already replicated (psum'd lookups), and the pmin
        # collapses any would-be divergence into "all take the slow
        # tier" instead of a cross-shard collective mismatch
        ok = lax.pmin(ok.astype(jnp.int32), shard.axis) > 0

    def fast(_):
        return _pipeline_fast_finish(
            tbl, rpkts, now, alive, drop_ip4, hits, sess_hit_idx,
            nat_reversed, nat_hit_idx, sweep_stride=sweep_stride,
            ml_mode=ml_mode, ml_kind=ml_kind, tel_mode=tel_mode,
            tnt_mode=tnt_mode, fib_fn=fib_fn, shard=shard, tid=tid,
            tnt_dropped=tnt_dropped, overlay=overlay,
            ovl_dropped=ovl_dropped, ovl_decapped=ovl_decapped,
        )

    def full(_):
        # the full chain re-derives its own ingress masks (and the
        # overlay decap) from orig_pkts (identical by construction)
        # but takes the ALREADY-CONSUMED tenant trio — tokens are
        # never spent twice
        return pipeline_step(tables, orig_pkts, now, acl_global_fn,
                             acl_local_fn, sweep_stride=sweep_stride,
                             ml_mode=ml_mode, ml_kind=ml_kind,
                             tel_mode=tel_mode, tnt_mode=tnt_mode,
                             fib_fn=fib_fn, sess_impl=sess_impl,
                             sess_hash=sess_hash, shard=shard,
                             overlay=overlay, ovl_inner=ovl_inner,
                             ovl_vni=ovl_vni,
                             _tnt_pre=((tid, tnt_dropped, tbl)
                                       if tnt else None))

    return lax.cond(ok, fast, full, None)


def _classifier_fns(impl: str):
    """(global, local) classify functions of one implementation name.
    Only BV swaps the LOCAL classify too — the MXU kernel is a
    global-table reformulation (bit-plane matmul doesn't gather
    per-packet tables), so mxu keeps the dense local path."""
    if impl == "mxu":
        from vpp_tpu.ops.acl_mxu import acl_classify_global_mxu

        return acl_classify_global_mxu, acl_classify_local
    if impl == "bv":
        from vpp_tpu.ops.acl_bv import (
            acl_classify_global_bv,
            acl_classify_local_bv,
        )

        return acl_classify_global_bv, acl_classify_local_bv
    if impl == "pallas":
        # ISSUE 16: the fused BV word-AND + first-set kernel rung.
        # The functions dispatch internally (ops/_pallas.use_pallas):
        # off-TPU they ARE the bv rung, so a pallas-knobbed config
        # stays bit-exact on the CPU harness.
        from vpp_tpu.ops.acl_bv import (
            acl_classify_global_pallas,
            acl_classify_local_pallas,
        )

        return acl_classify_global_pallas, acl_classify_local_pallas
    if impl != "dense":
        raise ValueError(f"unknown classifier impl {impl!r}")
    return acl_classify_global, acl_classify_local


def _fib_fn(fib_impl: str):
    """The ip4-lookup implementation of one ladder rung (the
    _classifier_fns twin — ops/fib.py dense masked-compare,
    ops/lpm.py binary-search-over-prefix-lengths, or its fused pallas
    form; docs/ROUTING.md, docs/KERNELS.md)."""
    if fib_impl == "lpm":
        from vpp_tpu.ops.lpm import fib_lookup_lpm

        return fib_lookup_lpm
    if fib_impl == "pallas":
        from vpp_tpu.ops.lpm import fib_lookup_lpm_fused

        return fib_lookup_lpm_fused
    if fib_impl != "dense":
        raise ValueError(f"unknown fib impl {fib_impl!r}")
    return fib_lookup_dense


@functools.lru_cache(maxsize=None)
def make_pipeline_step(impl: str = "dense", skip_local: bool = False,
                       fast: bool = False,
                       sweep_stride: int = SWEEP_STRIDE_DEFAULT,
                       ml_mode: str = "off", ml_kind: str = "mlp",
                       tel_mode: str = "off", tnt_mode: str = "off",
                       fib_impl: str = "dense",
                       sess_impl: str = "gather",
                       sess_hash: str = "fwd",
                       overlay: str = "off"):
    """Compose one pipeline-step callable from the epoch's gates:
    classifier implementation (dense | mxu | bv), the policy-free
    local-classify skip, the two-tier fast-path dispatch, the session
    sweep stride, the ML-stage mode/kernel kind, and the telemetry
    mode (all trace-time static — part of the memo key, so two
    configs with different gates never share a program). The Dataplane
    builds (and jit-caches) its step variants exclusively through
    here, so every (impl, skip, tier, stride, ml, tel) combination
    shares ONE chain definition — a pipeline edit can't diverge a
    variant.

    Memoized: equal gates return the SAME function object, so jax's
    function-identity tracing/compilation caches are shared across
    every Dataplane (and test) in the process — exactly as the old
    module-level step functions were. A fresh closure per caller
    would recompile the whole chain per dataplane instance."""
    from vpp_tpu.ops.acl import acl_local_none

    if ml_mode not in ("off", "score", "enforce"):
        raise ValueError(f"unknown ml_mode {ml_mode!r}")
    if ml_kind not in ("mlp", "forest"):
        raise ValueError(f"unknown ml_kind {ml_kind!r}")
    if tel_mode not in ("off", "latency", "full"):
        raise ValueError(f"unknown tel_mode {tel_mode!r}")
    if tnt_mode not in ("off", "on"):
        raise ValueError(f"unknown tnt_mode {tnt_mode!r}")
    if sess_impl not in ("gather", "pallas"):
        raise ValueError(f"unknown sess_impl {sess_impl!r}")
    if sess_hash not in ("fwd", "sym"):
        raise ValueError(f"unknown sess_hash {sess_hash!r}")
    if overlay not in ("off", "vxlan"):
        raise ValueError(f"unknown overlay {overlay!r}")
    acl_global_fn, acl_local_fn = _classifier_fns(impl)
    fib_fn = _fib_fn(fib_impl)
    if skip_local:
        acl_local_fn = acl_local_none
    base = pipeline_step_auto if fast else pipeline_step

    # jax-ok: overlay is trace-time static — it picks the step's CALL
    # SIGNATURE (the overlay form takes the host-parsed inner/vni
    # sidecar as explicit jit arguments), not a tracer branch
    if overlay == "off":
        def step(tables: DataplaneTables, pkts: PacketVector,
                 now: jnp.ndarray) -> StepResult:
            return base(tables, pkts, now, acl_global_fn=acl_global_fn,
                        acl_local_fn=acl_local_fn,
                        sweep_stride=sweep_stride,
                        ml_mode=ml_mode, ml_kind=ml_kind,
                        tel_mode=tel_mode,
                        tnt_mode=tnt_mode, fib_fn=fib_fn,
                        sess_impl=sess_impl, sess_hash=sess_hash)
    else:
        def step(tables: DataplaneTables, pkts: PacketVector,
                 now: jnp.ndarray, ovl_inner: PacketVector,
                 ovl_vni: jnp.ndarray) -> StepResult:
            return base(tables, pkts, now, acl_global_fn=acl_global_fn,
                        acl_local_fn=acl_local_fn,
                        sweep_stride=sweep_stride,
                        ml_mode=ml_mode, ml_kind=ml_kind,
                        tel_mode=tel_mode,
                        tnt_mode=tnt_mode, fib_fn=fib_fn,
                        sess_impl=sess_impl, sess_hash=sess_hash,
                        overlay=overlay, ovl_inner=ovl_inner,
                        ovl_vni=ovl_vni)

    step.__name__ = "pipeline_step_{}{}{}{}{}{}{}{}{}{}".format(
        impl, "_nolocal" if skip_local else "", "_auto" if fast else "",
        "" if ml_mode == "off" else f"_ml{ml_mode}"
        + ("_forest" if ml_kind == "forest" else ""),
        "" if tel_mode == "off" else f"_tel{tel_mode}",
        "" if tnt_mode == "off" else "_tenancy",
        "" if fib_impl == "dense" else f"_fib{fib_impl}",
        "" if sess_impl == "gather" else f"_sess{sess_impl}",
        "" if sess_hash == "fwd" else f"_h{sess_hash}",
        "" if overlay == "off" else f"_o{overlay}",
    )
    return step


pipeline_step_jit = jax.jit(pipeline_step, donate_argnums=())
