"""The TPU data-plane pipeline: packet vectors, device tables, fused step.

Reference analog: VPP's graph-node packet pipeline (256-packet frames
flowing dpdk-input → ethernet-input → ip4-input → acl → nat44 →
ip4-lookup → interface-tx; see SURVEY.md §3.5). Here each graph node is a
vectorized JAX/Pallas stage over a struct-of-arrays packet vector, the
whole chain is one jitted function, and tables live in HBM as a pytree
swapped transactionally by renderer commits.
"""

from vpp_tpu.pipeline.vector import VEC, Disposition, PacketVector, make_packet_vector
from vpp_tpu.pipeline.tables import (
    DataplaneConfig,
    DataplaneTables,
    InterfaceType,
)

__all__ = [
    "VEC",
    "Disposition",
    "PacketVector",
    "make_packet_vector",
    "DataplaneConfig",
    "DataplaneTables",
    "InterfaceType",
]
