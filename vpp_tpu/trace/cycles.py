"""Per-stage cycle accounting: the `show run` analog.

Reference: VPP's `show run` prints per-graph-node calls, vectors and
clocks/vector (docs/VPP_PACKET_TRACING_K8S.md:28-50). Under XLA the
production pipeline is ONE fused computation, so per-stage costs are
measured diagnostically: each stage is jitted and timed in isolation
over the same frame. The sum exceeds the fused step's time (fusion is
the point) — the per-stage numbers locate the expensive node, the fused
number is the real cost. For hardware-level truth use
``jax.profiler.trace`` (xplane) around ``Dataplane.process``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List

import jax
import jax.numpy as jnp

from vpp_tpu.ops.acl import acl_classify_global, acl_classify_local
from vpp_tpu.ops.fib import ip4_lookup
from vpp_tpu.ops.ip4 import ip4_input
from vpp_tpu.ops.nat44 import nat44_dnat, nat44_reverse, nat44_snat
from vpp_tpu.ops.session import session_insert, session_lookup_reverse
from vpp_tpu.pipeline.graph import pipeline_step
from vpp_tpu.pipeline.tables import DataplaneTables
from vpp_tpu.pipeline.vector import PacketVector


@dataclasses.dataclass
class StageTiming:
    node: str
    calls: int
    vectors: int          # packets per call
    seconds_per_call: float

    @property
    def ns_per_packet(self) -> float:
        if self.vectors == 0:
            return 0.0
        return self.seconds_per_call / self.vectors * 1e9


def _time(fn: Callable, args, iters: int) -> float:
    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def profile_stages(
    tables: DataplaneTables,
    pkts: PacketVector,
    now=None,
    iters: int = 20,
) -> List[StageTiming]:
    """Time each pipeline stage in isolation + the fused step.

    Stages take the tables/frame as real jit arguments (capturing device
    arrays in a closure embeds them as constants, which inflates per-call
    dispatch enormously). Absolute numbers still include one host→device
    dispatch each — compare rows, and trust the FUSED row as the real
    per-frame cost.
    """
    now = jnp.int32(1) if now is None else now
    n = int(pkts.src_ip.shape[0])
    alive = pkts.valid

    stages = {
        "ip4-input": (jax.jit(ip4_input), (pkts,)),
        "session-lookup": (jax.jit(session_lookup_reverse), (tables, pkts)),
        "nat44-reverse": (jax.jit(nat44_reverse), (tables, pkts, alive)),
        "nat44-dnat": (jax.jit(nat44_dnat), (tables, pkts, alive)),
        "acl-classify-local": (jax.jit(acl_classify_local), (tables, pkts)),
        "acl-classify-global": (jax.jit(acl_classify_global), (tables, pkts)),
        "ip4-lookup": (jax.jit(ip4_lookup), (tables, pkts.dst_ip)),
        # r3 additions to the step (the suspects of any r2->r3 headline
        # movement — VERDICT r3 Weak #2)
        "nat44-snat": (jax.jit(nat44_snat), (tables, pkts, alive)),
        "session-insert": (jax.jit(session_insert),
                           (tables, pkts, alive, now)),
        "FUSED pipeline-step": (jax.jit(pipeline_step), (tables, pkts, now)),
    }
    # BV classify rows only when the epoch carries a real interval-
    # bitmap structure (placeholder shapes mean the knob disabled BV)
    if int(tables.glb_bv_src.shape[0]) > 2:
        from vpp_tpu.ops.acl_bv import (
            acl_classify_global_bv,
            acl_classify_local_bv,
        )

        stages["acl-classify-global-bv"] = (
            jax.jit(acl_classify_global_bv), (tables, pkts))
        stages["acl-classify-local-bv"] = (
            jax.jit(acl_classify_local_bv), (tables, pkts))
    out = []
    for name, (fn, args) in stages.items():
        sec = _time(fn, args, iters)
        out.append(StageTiming(
            node=name, calls=iters, vectors=n, seconds_per_call=sec,
        ))
    return out


def format_show_run(timings: List[StageTiming]) -> str:
    """`show run`-style table."""
    header = (
        f"{'Node':<24}{'Calls':>8}{'Vectors':>10}"
        f"{'us/call':>12}{'ns/packet':>12}"
    )
    lines = [header, "-" * len(header)]
    for t in timings:
        lines.append(
            f"{t.node:<24}{t.calls:>8}{t.vectors:>10}"
            f"{t.seconds_per_call * 1e6:>12.2f}{t.ns_per_packet:>12.2f}"
        )
    return "\n".join(lines)
