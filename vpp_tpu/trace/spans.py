"""Control-plane span tracer: trace-id'd spans over the config path.

The paper's NB pipeline turns a K8s state change into programmed
dataplane tables through a chain of stages — KSR reflector event →
kvstore put → watch delivery → agent watcher dispatch → policy/service
render → ``ConfigTxn`` stage + epoch swap — and per-stage attribution
of that path is exactly what per-packet dataplanes obsess over on the
data path (Taurus, arxiv 2002.08987; nanoPU, arxiv 2212.06658). This
module is the control-plane analog of the packet tracer
(``trace/tracer.py``): spans instead of packets, a bounded in-memory
flight recorder instead of a trace ring.

Design:

  * **Spans** carry (trace_id, span_id, parent_id, stage, name, wall
    start, duration, attrs). ``stage`` is the coarse pipeline position
    ("ksr", "kvstore", "agent", "render", "txn", "swap", "cni", ...);
    ``name`` is the human line ("reflector put k8s/pod/default/web").
  * **Context** propagates through a thread-local span stack: the
    kvstore's synchronous watch delivery runs the whole chain on the
    writer's thread, so a root span opened at the KSR reflector (or the
    CNI server) automatically parents every downstream stage with zero
    plumbing through intermediate signatures. Cross-process hops
    (RemoteKVStore) drop the linkage — each process then records its
    local sub-trace.
  * **Recorder** is one module-level bounded deque (``RECORDER``), the
    `api-trace`-style always-on recorder: config events are rare, so
    recording is unconditional and costs two perf_counter reads per
    span. Layers that would fire per-watch-delivery guard on
    ``active()`` (a thread-local read) so un-traced store traffic pays
    a single dict lookup.

``Dataplane.swap()`` closes the loop: when a swap publishes under an
active trace, it observes ``now - root.t_wall`` into the agent's
``vpp_tpu_config_propagation_seconds`` histogram — the config
propagation latency SLO (event timestamp → epoch-swap complete).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

_local = threading.local()


def _stack() -> List["Span"]:
    s = getattr(_local, "stack", None)
    if s is None:
        s = _local.stack = []
    return s


@dataclass
class Span:
    trace_id: str
    span_id: int
    parent_id: Optional[int]
    stage: str
    name: str
    t_wall: float                 # wall-clock start (time.time)
    t0: float                     # perf_counter start
    duration: float = -1.0        # seconds; -1 = still open
    attrs: Dict[str, object] = field(default_factory=dict)

    @property
    def done(self) -> bool:
        return self.duration >= 0.0


class SpanTracer:
    """Bounded flight recorder of finished spans + the begin/end API.

    Thread-safe; spans nest via the thread-local context stack, so
    ``begin`` on one thread must be ``end``ed on the same thread (the
    config path is synchronous — see module doc)."""

    def __init__(self, max_spans: int = 4096):
        self.max_spans = max_spans
        self._buf: Deque[Span] = deque(maxlen=max_spans)
        self._lock = threading.Lock()
        self._span_ids = itertools.count(1)
        self._trace_ids = itertools.count(1)

    # --- recording ---
    def begin(self, stage: str, name: str, **attrs: object) -> Span:
        stack = _stack()
        parent = stack[-1] if stack else None
        span = Span(
            trace_id=(parent.trace_id if parent is not None
                      else f"t{next(self._trace_ids):06d}"),
            span_id=next(self._span_ids),
            parent_id=parent.span_id if parent is not None else None,
            stage=stage,
            name=name,
            t_wall=time.time(),
            t0=time.perf_counter(),
            attrs=dict(attrs),
        )
        stack.append(span)
        return span

    def end(self, span: Span) -> Span:
        span.duration = time.perf_counter() - span.t0
        stack = _stack()
        if stack and stack[-1] is span:
            stack.pop()
        else:  # out-of-order end (exception unwinding): drop by identity
            try:
                stack.remove(span)
            except ValueError:
                pass
        with self._lock:
            self._buf.append(span)
        return span

    @contextmanager
    def span(self, stage: str, name: str, **attrs: object):
        s = self.begin(stage, name, **attrs)
        try:
            yield s
        finally:
            self.end(s)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    # --- readback ---
    def entries(self) -> List[Span]:
        with self._lock:
            return list(self._buf)

    def traces(self) -> "Dict[str, List[Span]]":
        """Finished spans grouped by trace, each trace's spans sorted by
        start time (pipeline order), traces ordered by first start."""
        by_trace: Dict[str, List[Span]] = {}
        for s in self.entries():
            by_trace.setdefault(s.trace_id, []).append(s)
        for spans_ in by_trace.values():
            spans_.sort(key=lambda s: s.t0)
        return dict(sorted(by_trace.items(),
                           key=lambda kv: kv[1][0].t0))

    def format_traces(self, limit: int = 10) -> str:
        """`show spans` body: the most recent ``limit`` traces, one
        stage-tagged line per span, offsets relative to trace start."""
        traces = list(self.traces().items())
        if not traces:
            return "no spans recorded"
        lines: List[str] = []
        for trace_id, spans_ in traces[-limit:]:
            t0 = min(s.t0 for s in spans_)
            total = max(s.t0 + max(s.duration, 0.0) for s in spans_) - t0
            root = next((s for s in spans_ if s.parent_id is None),
                        spans_[0])
            lines.append(
                f"trace {trace_id} ({len(spans_)} spans, "
                f"{total * 1e3:.2f} ms) {root.name}"
            )
            for s in spans_:
                attrs = ""
                if s.attrs:
                    attrs = "  " + " ".join(
                        f"{k}={v}" for k, v in sorted(s.attrs.items())
                    )
                lines.append(
                    f"  [{s.stage:<8}] +{(s.t0 - t0) * 1e3:8.3f}ms "
                    f"{s.duration * 1e3:8.3f}ms  {s.name}{attrs}"
                )
        lines.append(f"{len(traces)} traces recorded, showing last "
                     f"{min(limit, len(traces))}")
        return "\n".join(lines)

    def to_json(self) -> str:
        """`/debug/spans` body: recorded timelines grouped by trace."""
        import json

        traces = []
        for trace_id, spans_ in self.traces().items():
            t0 = min(s.t0 for s in spans_)
            traces.append({
                "trace_id": trace_id,
                "spans": [
                    {
                        "stage": s.stage,
                        "name": s.name,
                        "span_id": s.span_id,
                        "parent_id": s.parent_id,
                        "start_ms": round((s.t0 - t0) * 1e3, 4),
                        "duration_ms": round(max(s.duration, 0.0) * 1e3, 4),
                        "wall_ts": s.t_wall,
                        "attrs": {str(k): str(v)
                                  for k, v in s.attrs.items()},
                    }
                    for s in spans_
                ],
            })
        return json.dumps({"traces": traces})

    def epoch_timings(self) -> Dict[object, Tuple[str, Dict[str, float]]]:
        """swap-epoch → (trace_id, stage → summed EXCLUSIVE seconds)
        over one consistent snapshot — the `show config-history` /
        /debug/txns join (the swap span carries the epoch it
        published).

        Config-path spans are fully nested (ksr wraps kvstore wraps
        agent …), so aggregating raw durations would report every
        upstream stage as "slow" whenever the innermost one is. The
        join therefore aggregates self-time: a span's duration minus
        its direct children's (clamped at 0 — a child evicted from the
        bounded buffer just costs attribution, never negative time)."""
        out: Dict[object, Tuple[str, Dict[str, float]]] = {}
        for trace_id, spans_ in self.traces().items():
            child_sum: Dict[int, float] = {}
            for s in spans_:
                if s.parent_id is not None:
                    child_sum[s.parent_id] = (
                        child_sum.get(s.parent_id, 0.0) + max(s.duration, 0.0)
                    )
            agg: Dict[str, float] = {}
            for s in spans_:
                self_time = max(
                    max(s.duration, 0.0) - child_sum.get(s.span_id, 0.0), 0.0
                )
                agg[s.stage] = agg.get(s.stage, 0.0) + self_time
            for s in spans_:
                if s.stage == "swap" and "epoch" in s.attrs:
                    out[s.attrs["epoch"]] = (trace_id, agg)
        return out


# the process-wide flight recorder every layer records into (the
# `api-trace { on }` discipline: always armed, bounded memory)
RECORDER = SpanTracer()


def active() -> bool:
    """True when the calling thread is inside a span (cheap guard for
    per-event layers like the kvstore watch fan-out)."""
    s = getattr(_local, "stack", None)
    return bool(s)


def current_span() -> Optional[Span]:
    s = getattr(_local, "stack", None)
    return s[-1] if s else None


def current_root() -> Optional[Span]:
    """The root span of the calling thread's active trace (its t_wall
    is the config event timestamp the propagation SLO measures from)."""
    s = getattr(_local, "stack", None)
    return s[0] if s else None
