"""PacketTracer: a bounded ring of sampled per-packet pipeline paths.

Reference analog: VPP's packet tracer — `trace add dpdk-input 50`
captures the next 50 packets with their node-by-node path; `show trace`
prints them (docs/VPP_PACKET_TRACING_K8S.md:20-50). Here the "path" is
reconstructed from the fused step's per-packet outputs (drop cause,
session/DNAT flags, disposition), so arming the tracer costs nothing on
the device: tracing reads back arrays the step already produced.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Deque, List

import numpy as np

from vpp_tpu.pipeline.graph import DROP_CAUSE_NAMES, StepResult
from vpp_tpu.pipeline.vector import Disposition, ip4_str


@dataclasses.dataclass(frozen=True)
class TraceEntry:
    frame_seq: int
    slot: int              # packet lane within the frame
    src: str
    dst: str
    proto: int
    sport: int
    dport: int
    rx_if: int
    path: tuple            # node names the packet visited
    disposition: str
    tx_if: int
    drop_cause: str

    def format(self) -> str:
        l4 = f"{self.sport}->{self.dport}" if self.proto in (6, 17) else ""
        lines = [
            f"Packet (frame {self.frame_seq}, slot {self.slot}): "
            f"proto {self.proto} {self.src} -> {self.dst} {l4}".rstrip(),
        ]
        for node in self.path:
            lines.append(f"  {node}")
        return "\n".join(lines)


class PacketTracer:
    """Arm with ``add(count)``; feed every processed frame to
    ``record``; read back with ``entries()`` / ``format_trace()``."""

    def __init__(self, max_entries: int = 512):
        self.max_entries = max_entries
        self._buf: Deque[TraceEntry] = deque(maxlen=max_entries)
        self._armed = 0
        self._frame_seq = 0
        self._lock = threading.Lock()

    def add(self, count: int = 50) -> None:
        """Capture the next ``count`` valid packets (VPP `trace add`)."""
        with self._lock:
            self._armed = min(count, self.max_entries)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._armed = 0

    @property
    def armed(self) -> int:
        # unlocked: lock-free peek on the per-frame hot path — a stale
        # read only starts/stops capture one frame late, and record()
        # re-checks under the lock before touching the buffer
        return self._armed

    def record(self, result: StepResult) -> int:
        """Sample packets from a processed frame while armed. Returns
        how many packets were captured from this frame."""
        with self._lock:
            if self._armed <= 0:
                self._frame_seq += 1
                return 0
            seq = self._frame_seq
            self._frame_seq += 1
        pkts = result.pkts
        valid = np.asarray(pkts.valid)
        idxs = np.nonzero(valid)[0]
        if idxs.size == 0:
            return 0
        disp = np.asarray(result.disp)
        tx_if = np.asarray(result.tx_if)
        node_id = np.asarray(result.node_id)
        cause = np.asarray(result.drop_cause)
        established = np.asarray(result.established)
        dnat = np.asarray(result.dnat_applied)
        # per-packet ML stage (ISSUE 10; PR-11 satellite): when the
        # step scored this batch, render an ml-score node with the raw
        # score (StepResult.ml_scores — zeros with the stage off) and
        # attribute DROP_ML verdicts to their own error-drop leaf
        ml_on = int(np.asarray(result.stats.ml_scored)) > 0
        ml_scores = np.asarray(result.ml_scores)
        ml_flagged = np.asarray(result.ml_flagged)
        src = np.asarray(pkts.src_ip)
        dst = np.asarray(pkts.dst_ip)
        proto = np.asarray(pkts.proto)
        sport = np.asarray(pkts.sport)
        dport = np.asarray(pkts.dport)
        rx_if = np.asarray(pkts.rx_if)

        captured = 0
        with self._lock:
            for i in idxs:
                if self._armed <= 0:
                    break
                i = int(i)
                path: List[str] = ["ip4-input"]
                c = int(cause[i])
                d = int(disp[i])
                if c == 1:  # DROP_IP4
                    path.append("error-drop (ip4-input)")
                elif c == 7:  # DROP_TENANT (ISSUE 14): the per-tenant
                    # token bucket drops right after ip4-input, BEFORE
                    # session lookup / ML / NAT / ACL — no later stage
                    # ever saw the packet
                    path.append("tenant-limit")
                    path.append("error-drop (tenant-quota)")
                else:
                    if established[i]:
                        path.append("session-lookup (established)")
                    # the ML stage evaluates on the post-NAT-reverse
                    # header, BEFORE DNAT/classify (graph._ml_eval);
                    # its drop verdict folds after the ACL's, so the
                    # ml-drop leaf renders below acl-classify
                    if ml_on:
                        path.append(
                            "ml-score (score {}{})".format(
                                int(ml_scores[i]),
                                ", flagged" if ml_flagged[i] else ""))
                    if dnat[i]:
                        path.append("nat44-dnat")
                    path.append("acl-classify")
                    if c == 2:
                        path.append("error-drop (acl-deny)")
                    elif c == 6:  # DROP_ML (deny beat it already)
                        path.append("error-drop (ml-drop)")
                    else:
                        path.append("ip4-lookup")
                        if c == 3:
                            path.append("error-drop (no-route)")
                        elif c == 4:
                            path.append("error-drop (fib-drop)")
                        elif d == int(Disposition.REMOTE):
                            path.append("vxlan/ici-encap")
                            path.append("interface-output (uplink)")
                        elif d == int(Disposition.HOST):
                            path.append("host-punt")
                        else:
                            path.append(
                                f"interface-output (if {int(tx_if[i])})"
                            )
                self._buf.append(TraceEntry(
                    frame_seq=seq,
                    slot=i,
                    src=ip4_str(int(src[i])),
                    dst=ip4_str(int(dst[i])),
                    proto=int(proto[i]),
                    sport=int(sport[i]),
                    dport=int(dport[i]),
                    rx_if=int(rx_if[i]),
                    path=tuple(path),
                    disposition=Disposition(d).name,
                    tx_if=int(tx_if[i]),
                    drop_cause=DROP_CAUSE_NAMES.get(c, str(c)),
                ))
                self._armed -= 1
                captured += 1
        return captured

    def entries(self) -> List[TraceEntry]:
        with self._lock:
            return list(self._buf)

    def format_trace(self) -> str:
        """`show trace` analog."""
        entries = self.entries()
        if not entries:
            return "No packets in trace buffer"
        return "\n------\n".join(e.format() for e in entries)
