"""Tracing & profiling.

Reference analogs: the VPP packet tracer (`trace add <node> N` + `show
trace`, docs/VPP_PACKET_TRACING_K8S.md:20-50), per-graph-node cycle
accounting (`show run` clocks/vector, :28-50), and — new in the
control-plane observability layer — span tracing over the config path
(``vpp_tpu.trace.spans``).

Re-exports resolve lazily (PEP 562): the packet tracer pulls in the
jax-backed pipeline, and light processes (kvserver, KSR) that only need
``trace.spans`` must not pay that import.
"""

_LAZY = {
    "PacketTracer": ("vpp_tpu.trace.tracer", "PacketTracer"),
    "TraceEntry": ("vpp_tpu.trace.tracer", "TraceEntry"),
    "StageTiming": ("vpp_tpu.trace.cycles", "StageTiming"),
    "profile_stages": ("vpp_tpu.trace.cycles", "profile_stages"),
    "format_show_run": ("vpp_tpu.trace.cycles", "format_show_run"),
    "Span": ("vpp_tpu.trace.spans", "Span"),
    "SpanTracer": ("vpp_tpu.trace.spans", "SpanTracer"),
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value  # cache for subsequent lookups
    return value
