"""Tracing & profiling.

Reference analogs: the VPP packet tracer (`trace add <node> N` + `show
trace`, docs/VPP_PACKET_TRACING_K8S.md:20-50) and per-graph-node cycle
accounting (`show run` clocks/vector, :28-50).
"""

from vpp_tpu.trace.tracer import PacketTracer, TraceEntry
from vpp_tpu.trace.cycles import StageTiming, profile_stages, format_show_run

__all__ = [
    "PacketTracer",
    "StageTiming",
    "TraceEntry",
    "format_show_run",
    "profile_stages",
]
