"""Distributed node-local IPAM (no central allocator)."""

from vpp_tpu.ipam.ipam import IPAM, IpamConfig

__all__ = ["IPAM", "IpamConfig"]
