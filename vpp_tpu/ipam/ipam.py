"""Node-local IPAM: node-ID ⊕ subnet arithmetic, no central allocator.

Every node derives its own address blocks purely from its cluster-unique
node ID and the shared IPAM config — pod network, VPP↔host interconnect
network, node interconnect IP, VXLAN IP — so cluster-wide IPAM is fully
distributed (SURVEY.md §2.4 "Cluster-wide address sharding").

Scheme (reference: plugins/contiv/ipam/doc.go:1-21, ipam.go):
  pod_subnet (e.g. 10.1.0.0/16) + node_id -> per-node pod network
  (10.1.<id>.0/24); pod IPs allocated from .2 upward (.1 = gateway);
  host interconnect subnet likewise; node/VXLAN interconnect IP =
  CIDR base + node_id (truncated to the free host bits).

Allocation state (pod-IP ↔ pod-ID map) is persisted through a kvstore
broker so an agent restart reconstructs assignments (ipam/persist.go).
"""

from __future__ import annotations

import ipaddress
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from vpp_tpu.kvstore.store import Broker

# seq index 1 within a pod network is the gateway, never assigned to pods
_GATEWAY_SEQ = 1

_PERSIST_PREFIX = "ipam/"


@dataclass
class IpamConfig:
    """Shared cluster IPAM configuration (reference: ipam.go Config +
    defaults from k8s/contiv-vpp.yaml ConfigMap)."""

    pod_subnet_cidr: str = "10.1.0.0/16"
    pod_network_prefix_len: int = 24
    pod_if_ip_cidr: str = "10.2.1.0/24"
    vpp_host_subnet_cidr: str = "172.30.0.0/16"
    vpp_host_network_prefix_len: int = 24
    node_interconnect_cidr: str = "192.168.16.0/24"
    node_interconnect_dhcp: bool = False
    vxlan_cidr: str = "192.168.30.0/24"
    service_cidr: str = "10.96.0.0/12"


def _apply_node_id(
    subnet: ipaddress.IPv4Network, node_id: int, network_prefix_len: int
) -> ipaddress.IPv4Network:
    """Carve the per-node /network_prefix_len block out of the subnet by
    placing the node ID into the intermediate bits.

    Unlike the reference (which silently truncates the ID,
    ipam.go convertToNodeIPPart), an ID that does not fit the available
    node bits is an error — truncation would give two nodes overlapping
    pod networks with no warning.
    """
    node_bits = network_prefix_len - subnet.prefixlen
    if node_bits < 0:
        raise ValueError(
            f"network prefix /{network_prefix_len} is wider than subnet {subnet}"
        )
    if node_bits < 32 and node_id >= (1 << node_bits):
        raise ValueError(
            f"node ID {node_id} does not fit the {node_bits} node bits of "
            f"{subnet} with per-node /{network_prefix_len} networks"
        )
    base = int(subnet.network_address) + (node_id << (32 - network_prefix_len))
    return ipaddress.ip_network((base, network_prefix_len))


def _host_ip_in(cidr: ipaddress.IPv4Network, node_id: int) -> ipaddress.IPv4Address:
    """CIDR base + node_id. Raises if the ID does not fit the host bits
    (same no-silent-collision stance as _apply_node_id) or would be the
    broadcast address."""
    host_bits = 32 - cidr.prefixlen
    if node_id >= (1 << host_bits) - 1:
        raise ValueError(
            f"node ID {node_id} does not fit as a host address in {cidr}"
        )
    return ipaddress.ip_address(int(cidr.network_address) + node_id)


class IPAM:
    """See module docstring. Thread-safe."""

    def __init__(
        self,
        node_id: int,
        config: Optional[IpamConfig] = None,
        broker: Optional[Broker] = None,
    ):
        if not 0 < node_id < 256:
            raise ValueError(f"node_id must be in 1..255, got {node_id}")
        self._lock = threading.RLock()
        self.node_id = node_id
        self.config = config or IpamConfig()
        self.broker = broker
        c = self.config

        self.pod_subnet = ipaddress.ip_network(c.pod_subnet_cidr)
        self.pod_network = _apply_node_id(
            self.pod_subnet, node_id, c.pod_network_prefix_len
        )
        self.pod_if_ip_cidr = ipaddress.ip_network(c.pod_if_ip_cidr)
        self.vpp_host_subnet = ipaddress.ip_network(c.vpp_host_subnet_cidr)
        self.vpp_host_network = _apply_node_id(
            self.vpp_host_subnet, node_id, c.vpp_host_network_prefix_len
        )
        self.node_interconnect_cidr = ipaddress.ip_network(c.node_interconnect_cidr)
        self.vxlan_cidr = ipaddress.ip_network(c.vxlan_cidr)
        self.service_network = ipaddress.ip_network(c.service_cidr)

        # assigned pod IPs: uint32 -> pod id string
        self._assigned: Dict[int, str] = {}
        self._last_assigned = 1
        if broker is not None:
            self._load_assigned()

    # --- derived addresses ---
    def pod_gateway_ip(self) -> ipaddress.IPv4Address:
        """.1 of the node's pod network (default GW for pods)."""
        return ipaddress.ip_address(int(self.pod_network.network_address) + _GATEWAY_SEQ)

    def veth_vpp_end_ip(self) -> ipaddress.IPv4Address:
        """VPP-side address of the VPP↔host interconnect (x.y.z.1)."""
        return ipaddress.ip_address(int(self.vpp_host_network.network_address) + 1)

    def veth_host_end_ip(self) -> ipaddress.IPv4Address:
        """Host-side address of the VPP↔host interconnect (x.y.z.2)."""
        return ipaddress.ip_address(int(self.vpp_host_network.network_address) + 2)

    def node_ip_address(self, node_id: Optional[int] = None) -> ipaddress.IPv4Address:
        return _host_ip_in(self.node_interconnect_cidr, node_id or self.node_id)

    def node_ip_with_prefix(self, node_id: Optional[int] = None) -> ipaddress.IPv4Interface:
        return ipaddress.ip_interface(
            f"{self.node_ip_address(node_id)}/{self.node_interconnect_cidr.prefixlen}"
        )

    def vxlan_ip_address(self, node_id: Optional[int] = None) -> ipaddress.IPv4Address:
        return _host_ip_in(self.vxlan_cidr, node_id or self.node_id)

    def other_node_pod_network(self, node_id: int) -> ipaddress.IPv4Network:
        return _apply_node_id(
            self.pod_subnet, node_id, self.config.pod_network_prefix_len
        )

    def other_node_vpp_host_network(self, node_id: int) -> ipaddress.IPv4Network:
        return _apply_node_id(
            self.vpp_host_subnet, node_id, self.config.vpp_host_network_prefix_len
        )

    # --- pod IP allocation ---
    def next_pod_ip(self, pod_id: str) -> ipaddress.IPv4Address:
        """Allocate the next free pod IP, persisting the assignment.

        Scans from just past the last assignment (wrapping), skipping the
        gateway — same rotation as the reference (ipam.go:261-298) so
        recently released addresses are not immediately reused.
        """
        if not pod_id:
            raise ValueError("pod ID must be non-empty (used to release the IP)")
        with self._lock:
            base = int(self.pod_network.network_address)
            # seq 0 = network address, last = broadcast: never assigned.
            max_seq = self.pod_network.num_addresses - 1
            order = list(range(self._last_assigned + 1, max_seq)) + list(
                range(1, self._last_assigned + 1)
            )
            for seq in order:
                if seq == _GATEWAY_SEQ:
                    continue
                ip = base + seq
                if ip in self._assigned:
                    continue
                self._assigned[ip] = pod_id
                self._last_assigned = seq
                self._save_assigned(ip, pod_id)
                return ipaddress.ip_address(ip)
            raise RuntimeError(
                f"no free pod IP in {self.pod_network} (all assigned)"
            )

    def release_pod_ip(self, pod_id: str) -> bool:
        """Release the IP assigned to the pod; True if one was found."""
        if not pod_id:
            return False
        with self._lock:
            for ip, pid in list(self._assigned.items()):
                if pid == pod_id:
                    del self._assigned[ip]
                    if self.broker is not None:
                        self.broker.delete(_PERSIST_PREFIX + pod_id)
                    return True
            return False

    def get_pod_ip(self, pod_id: str) -> Optional[ipaddress.IPv4Address]:
        with self._lock:
            for ip, pid in self._assigned.items():
                if pid == pod_id:
                    return ipaddress.ip_address(ip)
            return None

    def assigned_count(self) -> int:
        with self._lock:
            return len(self._assigned)

    # --- persistence (reference: ipam/persist.go) ---
    def _save_assigned(self, ip: int, pod_id: str) -> None:
        if self.broker is not None:
            self.broker.put(_PERSIST_PREFIX + pod_id, {"ip": ip, "pod": pod_id})

    def _load_assigned(self) -> None:
        base = int(self.pod_network.network_address)
        max_seq = self.pod_network.num_addresses - 1
        for key, item in self.broker.list_values(_PERSIST_PREFIX).items():
            ip = int(item["ip"])
            seq = ip - base
            if not 0 < seq < max_seq:
                # Persisted entry from a different pod network (e.g. the
                # node came back with a new ID): stale — drop it rather
                # than poisoning the allocator bounds.
                self.broker.delete(key)
                continue
            self._assigned[ip] = item["pod"]
            if seq > self._last_assigned:
                self._last_assigned = seq
