"""Reflex-plane latency governor: closed-loop SLO protection for the
wire path (ISSUE 13 tentpole; ROADMAP item 3).

The ring wire path (PR 7) fixed throughput, but the pump's window
shaping was still open-loop: the stager ships whatever backlog is
queued, so under load every frame pays the full S-slot window's
batching latency and p99 sits wherever the offered load pushes it
(``io_wire_persistent_lat_p99_us`` 2557 in BENCH_r05). nanoPU
(PAPERS.md) argues the metric that matters for reflex traffic — DDoS
mitigation verdicts, health checks, our ML ``enforce`` decisions — is
wire-to-wire *tail* latency; Gryphon shows the failure mode at the
other end: a gateway that cannot shed or prioritize under overload
fails everyone instead of degrading gracefully.

:class:`LatencyGovernor` closes the loop **host-side only**. It
watches the signals PR 11 built (the in-step device latency histogram
behind ``vpp_tpu_wire_latency_seconds``, falling back to the pump's
host batch window), plus per-window fill occupancy and the rx backlog,
and adapts the pump's window shaping between its two existing extremes
— 1-slot lone-frame windows (the latency floor) and S-slot backlog
fills (throughput) — against an explicit ``latency_slo_us`` knob.
Critically, every actuator is a host-side integer the pump/stager
already treats as dynamic (window fill count, in-flight depth,
coalesce cap, admission), so the governor **never enters the jit
key**: governed and ungoverned runs trace the exact same step
variants (pinned by tests/test_governor.py).

Control law (docs/LATENCY.md round 13 has the derivation)::

    t_svc  : EWMA per-frame service time (delivered-frame deltas)
    est    = p99_obs + backlog_frames * t_svc        # SLO envelope
    hi     = slo_us;  lo = slo_us * (1 - hysteresis)

    p99_obs > hi and windows not already lone  ->  level - 1  (fast)
    est > hi and p99_obs <= lo and level < top ->  level + 1  (queue
                                                   pressure, headroom)
    est > hi otherwise, B consecutive ticks    ->  BROWNOUT (shed)
    est < lo for R consecutive ticks           ->  un-shed -> RECOVERY,
                                                   then level + 1 per R
                                                   ticks back to top
                                                   -> NORMAL

Levels are a discrete ladder from ``(fill=1, inflight=1)`` to
``(fill=S, inflight=max)``; one step per tick with a settle grace
between steps, hysteresis bands, and slow-up/fast-down asymmetry —
the anti-oscillation guards (a monotone trajectory within bands is
pinned by the anti-flap unit test). Brownout/recovery mirrors the
PR 8 degraded-mode pattern: brownout never snaps straight back to
normal (one-way brownout -> recovery -> normal), and
``vpp_tpu_degraded{component="governor"}`` flips ONLY when the
control loop itself is wedged (``governor.tick`` fault ladder) — a
wedged governor freezes the last-known window shape and the pump
keeps forwarding.

Overload shedding is explicit and attributed: in brownout the pump
admits bulk only up to the pipe's natural depth (``fill x inflight``
frames) and drops the excess at admission as ``drops_overload``
(``vpp_tpu_pump_drops_total{reason="overload"}``) — never silent
queue growth. :class:`PriorityFilter` designates the reflex flows
(static port/prefix/proto rules + dynamically marked host pairs, e.g.
ML-flagged traffic) that bypass shedding entirely and preempt bulk
windows in the staging path (the stager ships a window the moment a
priority slot lands instead of draining the backlog into it).

This module is jax-free on purpose (like io/rings.py): it runs on the
pump's dispatch thread and in light processes.
"""

from __future__ import annotations

import ipaddress
import logging
import threading
import time
from typing import Iterable, List, Optional, Tuple

import numpy as np

from vpp_tpu.testing import faults

log = logging.getLogger("governor")

# governor operating modes, in the order the state machine visits them
# (the vpp_tpu_governor_mode info gauge enumerates these plus "off"
# for a pump with no governor attached)
GOVERNOR_MODES = ("normal", "brownout", "recovery")

# consecutive tick failures before the governor declares itself wedged
# (one-way, vpp_tpu_degraded{component="governor"}): a single injected
# or transient failure skips one adjustment — the PR 8 fault ladders
# never trip on the first blip either
WEDGE_LIMIT = 3


class LatencyGovernor:
    """Closed-loop window-shape controller (module doc).

    Thread contract: ``maybe_tick`` runs on the pump's dispatch thread;
    ``limits``/``admit`` are read on the same thread; ``snapshot`` is
    read by the collector/CLI threads — every mutable field is guarded
    by ``_lock`` (ticks are rare and short, so the hot-path cost is an
    uncontended acquire).

    ``SNAPSHOT_SCALARS`` names the numeric snapshot keys the collector
    exports one gauge each for (``GOVERNOR_STAT_GAUGES``); the
    ``--counters`` lint pass keeps the two in lockstep.
    """

    SNAPSHOT_SCALARS = (
        "slo_us", "level", "fill", "inflight", "last_p99_us",
        "queue_est_us", "fill_avg", "ticks", "tick_errors",
    )

    def __init__(self, slo_us: float, *, slots: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 tick_s: float = 0.05, hysteresis_pct: float = 30.0,
                 brownout_ticks: int = 3, recover_ticks: int = 5,
                 settle_ticks: int = 2, ewma_alpha: float = 0.3,
                 shed_margin: float = 0.4,
                 clock=time.monotonic):
        if slo_us <= 0:
            raise ValueError(f"latency_slo_us must be > 0, got {slo_us}")
        if not 0.0 < hysteresis_pct < 100.0:
            raise ValueError(
                f"governor hysteresis_pct must be in (0, 100), "
                f"got {hysteresis_pct}")
        if brownout_ticks < 1 or recover_ticks < 1:
            raise ValueError("governor brownout/recover ticks must be >= 1")
        self.slo_us = float(slo_us)
        self.tick_s = float(tick_s)
        self.hysteresis_pct = float(hysteresis_pct)
        self.brownout_ticks = int(brownout_ticks)
        self.recover_ticks = int(recover_ticks)
        self.settle_ticks = int(settle_ticks)
        self.ewma_alpha = float(ewma_alpha)
        if not 0.0 < shed_margin <= 1.0:
            raise ValueError(
                f"governor shed_margin must be in (0, 1], "
                f"got {shed_margin}")
        self.shed_margin = float(shed_margin)
        self._clock = clock
        self._lock = threading.Lock()
        self._queue_cap: Optional[int] = None
        self._levels: List[Tuple[int, int]] = []
        self._level = 0
        self._fill = 1
        self._inflight = 1
        self._shed = False
        self.mode = "normal"
        self.wedged = False
        self._last_tick = float("-inf")
        self._over_ticks = 0
        self._under_ticks = 0
        self._ok_ticks = 0
        self._cool = 0
        self._error_streak = 0
        self._t_svc_s: Optional[float] = None
        self._rate_last: Optional[Tuple[float, int]] = None
        self._last_p99 = 0.0
        self._last_queue_est = 0.0
        self._last_fill_avg = 0.0
        self._ticks = 0
        self._tick_errors = 0
        self._adjust = {"up": 0, "down": 0}
        self._transitions = {m: 0 for m in GOVERNOR_MODES}
        if slots is not None and max_inflight is not None:
            self.bind(slots, max_inflight)

    # --- ladder ---
    def bind(self, slots: int, max_inflight: int,
             queue_cap: Optional[int] = None) -> None:
        """Build the level ladder for the pump's geometry: fill doubles
        1 -> slots first (the latency-dominant lever), then in-flight
        depth doubles to ``max_inflight``. Idempotent — the owning
        pump calls this at construction; an explicitly pre-bound
        governor (tests) keeps its ladder.

        ``queue_cap`` switches the governor into EXPRESS mode (the
        pump passes it when a priority lane is attached): reflex
        traffic bypasses the bulk queue entirely, so bulk backlog no
        longer counts toward the SLO envelope — the p99 axis shapes
        windows for the reflex lane, and brownout/shedding engage only
        when the backlog itself exceeds ``queue_cap`` frames (true
        overload: the queue would otherwise grow to ring overflow,
        which is silent loss at the daemon instead of attributed
        drops here)."""
        with self._lock:
            if queue_cap is not None:
                self._queue_cap = max(1, int(queue_cap))
            if self._levels:
                return
            slots = max(1, int(slots))
            infl = max(1, int(max_inflight))
            # the in-flight floor stays at 2 where the pump allows it:
            # depth 1 serializes the ring's double buffer (stage,
            # dispatch and fetch stop overlapping), which costs bulk
            # goodput far more than it buys the reflex lane — one
            # residual window of wait either way
            f, i = 1, min(2, infl)
            levels = [(f, i)]
            while f < slots or i < infl:
                if f < slots:
                    f = min(f * 2, slots)
                else:
                    i = min(i * 2, infl)
                levels.append((f, min(i, infl)))
            self._levels = levels
            # rest at the top of the ladder: the fill cap only binds
            # under backlog (a lone frame still ships alone), so full
            # throughput shape is the correct no-signal default
            self._level = len(levels) - 1
            self._fill, self._inflight = levels[self._level]

    # --- hot-path reads (pump dispatch thread) ---
    @property
    def fill(self) -> int:
        with self._lock:
            return self._fill

    def limits(self) -> Tuple[int, int, bool]:
        """``(window_fill, max_inflight, shedding)`` — the live
        actuator values the pump applies to its staging path."""
        with self._lock:
            return self._fill, self._inflight, self._shed

    def admit(self, priority: bool, backlog_frames: int) -> bool:
        """Admission decision for one coalesce group. Priority groups
        are ALWAYS admitted (the lane shedding protects). Bulk is
        admitted unconditionally outside brownout; in brownout it is
        admitted only while the backlog fits the SLO's queue budget —
        the deepest queue whose predicted FIFO delay
        (``backlog x t_svc``) still spends at most ``shed_margin`` of
        the SLO, floored at the pipe's natural depth
        (``fill x inflight`` frames, what keeps the device busy).
        Excess offered load is dropped at admission with an
        attributed cause instead of growing the queue without bound;
        offered load the SLO-budgeted queue CAN carry is never shed,
        which is what keeps bulk goodput at sub-saturating load."""
        if priority:
            return True
        with self._lock:
            if not self._shed:
                return True
            if self._queue_cap is not None:
                # express mode: bulk queueing no longer delays reflex
                # traffic, so the shed bound is the physical queue cap
                # — brownout trims the backlog to it, attributed
                return backlog_frames <= self._queue_cap
            bound = self._fill * self._inflight
            if self._t_svc_s:
                bound = max(bound, int(
                    self.shed_margin * self.slo_us
                    / max(self._t_svc_s * 1e6, 1e-9)))
            return backlog_frames <= bound

    # --- control loop ---
    def tick_due(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = self._clock()
        with self._lock:
            if self.wedged:
                return False
            return now - self._last_tick >= self.tick_s

    def maybe_tick(self, p99_us: Optional[float], backlog_frames: int,
                   delivered_frames: int,
                   fill_avg: Optional[float] = None,
                   now: Optional[float] = None) -> bool:
        """Run one control tick if due. Never raises: a failing tick
        (the ``governor.tick`` fault seam, or a real bug in the
        control loop) is counted, and after ``WEDGE_LIMIT`` consecutive
        failures the governor goes WEDGED — one-way: adjustments stop,
        the pump keeps running at the last-known window shape, and
        ``vpp_tpu_degraded{component="governor"}`` flips. A crashed
        governor must degrade observability, never the data path."""
        if now is None:
            now = self._clock()
        with self._lock:
            if self.wedged or now - self._last_tick < self.tick_s:
                return False
            self._last_tick = now
            try:
                self._tick_locked(p99_us, backlog_frames,
                                  delivered_frames, fill_avg, now)
                self._error_streak = 0
                return True
            except Exception:  # noqa: BLE001 — wedge ladder (module doc)
                self._tick_errors += 1
                self._error_streak += 1
                if self._error_streak >= WEDGE_LIMIT:
                    self.wedged = True
                    log.exception(
                        "governor wedged after %d consecutive tick "
                        "failures — window shape frozen at fill=%d "
                        "inflight=%d shed=%s",
                        self._error_streak, self._fill, self._inflight,
                        self._shed)
                else:
                    log.exception("governor tick failed (%d/%d)",
                                  self._error_streak, WEDGE_LIMIT)
                return False

    def _tick_locked(self, p99_us, backlog_frames, delivered_frames,
                     fill_avg, now) -> None:
        # faults: "governor.tick" = the control loop crashing (a bad
        # observation source, a wedged telemetry fetch) — it must
        # freeze the window shape, never kill the pump (chaos schedule)
        faults.fire("governor.tick")
        self._ticks += 1
        if not self._levels:
            return  # unbound (no pump yet): observe-only
        # EWMA per-frame service time from delivered-frame deltas —
        # the queue-delay estimator's slope. Idle gaps inflate the
        # instantaneous value; backlog is ~0 then, so the product
        # (queue_est) stays honest.
        if self._rate_last is not None:
            t0, d0 = self._rate_last
            dt, dd = now - t0, delivered_frames - d0
            if dd > 0 and dt > 0:
                inst = dt / dd
                self._t_svc_s = (inst if self._t_svc_s is None else
                                 self.ewma_alpha * inst
                                 + (1 - self.ewma_alpha) * self._t_svc_s)
        self._rate_last = (now, delivered_frames)
        queue_us = (backlog_frames * self._t_svc_s * 1e6
                    if self._t_svc_s else 0.0)
        p99 = float(p99_us) if p99_us is not None else None
        self._last_p99 = p99 or 0.0
        self._last_queue_est = queue_us
        if fill_avg is not None:
            self._last_fill_avg = float(fill_avg)
        hi = self.slo_us
        lo = self.slo_us * (1.0 - self.hysteresis_pct / 100.0)
        if self._queue_cap is not None:
            # EXPRESS mode (priority lane attached): reflex traffic
            # bypasses the bulk queue, so backlog does not count
            # toward the SLO envelope — p99 IS the envelope, and
            # queue pressure is a separate overload axis against the
            # physical queue bound
            est = p99 or 0.0
            queue_over = backlog_frames > self._queue_cap
            queue_clear = backlog_frames <= self._queue_cap // 2
        else:
            est = (p99 or 0.0) + queue_us
            queue_over = False
            queue_clear = True
        if self._cool > 0:
            self._cool -= 1
        top = len(self._levels) - 1
        if est <= hi and not queue_over:
            self._ok_ticks += 1
        else:
            self._ok_ticks = 0
        if est > hi or queue_over:
            self._under_ticks = 0
            if (p99 is not None and p99 > hi and self._level > 0
                    and self._cool == 0):
                self._step_locked(-1)   # batching latency: fast down
                self._over_ticks = 0
            elif ((p99 is None or p99 <= lo) and self._level < top
                  and self._cool == 0):
                self._step_locked(+1)   # queue pressure with headroom
                self._over_ticks = 0
            else:
                # count toward brownout only when no step could still
                # help (settling after a step is not "unattainable");
                # in express mode additionally only under QUEUE
                # pressure — shedding bulk cannot improve a reflex
                # lane that already bypasses the queue, so a p99-only
                # breach at the floor holds shape instead of shedding
                if self._cool == 0 and \
                        (self._queue_cap is None or queue_over):
                    self._over_ticks += 1
                if (not self._shed
                        and self._over_ticks >= self.brownout_ticks):
                    # SLO unattainable at offered load: shed bulk
                    self._shed = True
                    self._enter_locked("brownout")
        elif est < lo:
            self._over_ticks = 0
            self._under_ticks += 1
            if self._under_ticks >= self.recover_ticks:
                self._under_ticks = 0
                if self._shed and queue_clear:
                    # one-way: brownout exits INTO recovery, never
                    # straight back to normal (PR 8 pattern); in
                    # express mode the backlog must also have drained
                    # below half the queue bound, or shedding would
                    # flap against a still-standing queue
                    self._shed = False
                    self._enter_locked("recovery")
                elif not self._shed and self._level < top \
                        and self._cool == 0:
                    self._step_locked(+1)  # slow up: one step per R ticks
        else:
            # inside the hysteresis band: hold — this is the
            # anti-flap dead zone
            self._over_ticks = 0
            self._under_ticks = 0
        if (self.mode == "recovery" and not self._shed
                and self._level == top
                and self._ok_ticks >= self.recover_ticks):
            self._enter_locked("normal")

    def _step_locked(self, direction: int) -> None:
        new = min(max(self._level + direction, 0), len(self._levels) - 1)
        if new == self._level:
            return
        self._level = new
        self._fill, self._inflight = self._levels[new]
        self._adjust["up" if direction > 0 else "down"] += 1
        self._cool = self.settle_ticks

    def _enter_locked(self, mode: str) -> None:
        if mode == self.mode:
            return
        log.warning("governor %s -> %s (p99 %.0fus queue-est %.0fus "
                    "fill %d inflight %d)", self.mode, mode,
                    self._last_p99, self._last_queue_est, self._fill,
                    self._inflight)
        self.mode = mode
        self._transitions[mode] += 1
        self._ok_ticks = 0

    # --- observability ---
    def snapshot(self) -> dict:
        """Consistent copy for the collector/CLI (host scalars only)."""
        with self._lock:
            return {
                "mode": self.mode,
                "shedding": self._shed,
                "wedged": self.wedged,
                "slo_us": self.slo_us,
                "level": self._level,
                "levels": len(self._levels),
                "fill": self._fill,
                "inflight": self._inflight,
                "last_p99_us": self._last_p99,
                "queue_est_us": self._last_queue_est,
                "fill_avg": self._last_fill_avg,
                "t_svc_us": (self._t_svc_s or 0.0) * 1e6,
                "ticks": self._ticks,
                "tick_errors": self._tick_errors,
                "adjust_up": self._adjust["up"],
                "adjust_down": self._adjust["down"],
                "transitions": dict(self._transitions),
            }


class PriorityFilter:
    """Designates the reflex flows the priority lane serves.

    Static rules (config knobs ``io.priority_ports`` /
    ``io.priority_prefixes`` / ``io.priority_protos``) classify by
    L4 port (either direction), src/dst CIDR, or protocol number;
    :meth:`mark_flow` adds dynamic (src, dst) host pairs at runtime —
    the hook EXPOSED for an ML-mirror consumer to promote flagged
    flows without a config round trip (nothing in-tree calls it yet;
    the automatic ml_flagged→mark_flow wiring is ROADMAP item 4's
    online-loop territory). Marks are host-pair granular: the reflex
    unit the enforce path acts on.

    Classification is vectorized numpy over a frame's column block
    (<= VEC packets, a handful of rules — microseconds on the dispatch
    thread); a frame is priority when ANY of its packets match.
    """

    def __init__(self, ports: Iterable[int] = (),
                 prefixes: Iterable[str] = (),
                 protos: Iterable[int] = (),
                 max_flows: int = 4096):
        ports = sorted({int(p) for p in ports})
        protos = sorted({int(p) for p in protos})
        # a rule that can never match is the misconfiguration class
        # validate_governor_config exists to refuse at YAML load —
        # same discipline as the CIDR parse below
        for p in ports:
            if not 0 < p <= 0xFFFF:
                raise ValueError(
                    f"priority_ports entries must be 1..65535, "
                    f"got {p}")
        for p in protos:
            if not 0 <= p <= 0xFF:
                raise ValueError(
                    f"priority_protos entries must be 0..255, got {p}")
        self.ports = np.asarray(ports, np.int64)
        self.protos = np.asarray(protos, np.int64)
        nets = []
        for cidr in prefixes:
            net = ipaddress.ip_network(str(cidr), strict=False)
            if net.version != 4:
                raise ValueError(
                    f"priority_prefixes must be IPv4, got {cidr!r}")
            nets.append((int(net.network_address),
                         int(net.netmask)))
        self._nets = tuple(nets)
        self.max_flows = int(max_flows)
        self._lock = threading.Lock()
        self._flows: set = set()
        # sorted packed (src<<32 | dst) keys for vectorized membership
        self._flow_keys = np.empty(0, np.uint64)

    @staticmethod
    def _pack(src_ip: int, dst_ip: int) -> int:
        return (int(src_ip) & 0xFFFFFFFF) << 32 | (int(dst_ip)
                                                   & 0xFFFFFFFF)

    def mark_flow(self, src_ip: int, dst_ip: int) -> bool:
        """Promote a (src, dst) host pair to the priority lane.
        Returns False (and keeps the existing set) when the mark table
        is full — a bounded set, so a flood of flagged flows cannot
        grow host memory without limit."""
        key = self._pack(src_ip, dst_ip)
        with self._lock:
            if key in self._flows:
                return True
            if len(self._flows) >= self.max_flows:
                return False
            self._flows.add(key)
            self._flow_keys = np.fromiter(
                sorted(self._flows), np.uint64, len(self._flows))
            return True

    def unmark_flow(self, src_ip: int, dst_ip: int) -> None:
        key = self._pack(src_ip, dst_ip)
        with self._lock:
            if key in self._flows:
                self._flows.discard(key)
                self._flow_keys = np.fromiter(
                    sorted(self._flows), np.uint64, len(self._flows))

    def flow_count(self) -> int:
        with self._lock:
            return len(self._flows)

    def prefix_count(self) -> int:
        """Number of static CIDR rules (CLI/observability; the
        internal representation is private)."""
        return len(self._nets)

    def match_mask(self, src_ip: np.ndarray, dst_ip: np.ndarray,
                   proto: np.ndarray, sport: np.ndarray,
                   dport: np.ndarray) -> np.ndarray:
        """Per-packet priority mask (bool [n]) over column arrays."""
        src = np.asarray(src_ip, np.uint32)
        dst = np.asarray(dst_ip, np.uint32)
        m = np.zeros(src.shape, bool)
        if self.ports.size:
            m |= np.isin(np.asarray(dport, np.int64), self.ports)
            m |= np.isin(np.asarray(sport, np.int64), self.ports)
        if self.protos.size:
            m |= np.isin(np.asarray(proto, np.int64), self.protos)
        for net, mask in self._nets:
            m |= (src & np.uint32(mask)) == np.uint32(net)
            m |= (dst & np.uint32(mask)) == np.uint32(net)
        with self._lock:
            keys = self._flow_keys
        if keys.size:
            packed = (src.astype(np.uint64) << np.uint64(32)
                      | dst.astype(np.uint64))
            m |= np.isin(packed, keys)
        return m

    def frame_match(self, frame) -> bool:
        """True when ANY of the frame's valid packets is priority."""
        n = frame.n
        if not n:
            return False
        c = frame.cols
        return bool(self.match_mask(
            c["src_ip"][:n], c["dst_ip"][:n], c["proto"][:n],
            c["sport"][:n], c["dport"][:n]).any())


def validate_governor_config(io_cfg) -> None:
    """Fail FAST on governor/priority misconfiguration at YAML load
    (cmd/config.py; the validate_ring_geometry pattern) — a bad knob
    is rejected when the config is read, not at the first pump tick."""
    slo = float(getattr(io_cfg, "latency_slo_us", 0) or 0)
    if slo < 0:
        raise ValueError(f"latency_slo_us must be >= 0, got {slo}")
    if slo > 0:
        # construct once: the ctor owns the bound checks
        LatencyGovernor(
            slo,
            tick_s=float(io_cfg.governor_tick_s),
            hysteresis_pct=float(io_cfg.governor_hysteresis_pct),
            brownout_ticks=int(io_cfg.governor_brownout_ticks),
            recover_ticks=int(io_cfg.governor_recover_ticks),
        )
        if float(io_cfg.governor_tick_s) <= 0:
            raise ValueError("governor_tick_s must be > 0")
    # priority rules parse (CIDR syntax) even with the governor off —
    # the lane works ungoverned too
    PriorityFilter(ports=getattr(io_cfg, "priority_ports", ()) or (),
                   prefixes=getattr(io_cfg, "priority_prefixes", ()) or (),
                   protos=getattr(io_cfg, "priority_protos", ()) or ())
