"""Fleet pump: the IO tier fronting N dataplane instances (ISSUE 18).

One dispatch side (the caller's thread — ``submit()``) partitions
packed frames through :class:`vpp_tpu.fleet.steering.FleetSteering`
and re-frames each instance's packets at the instance's native width;
one worker thread per instance drains a bounded queue into
``Dataplane.process_packed`` (the single-writer-per-instance law: the
worker is its instance's ONLY traffic source, so ``commit=True`` is
safe exactly like the DataplanePump it parallels).

Partial frames ride the ``flags`` valid bit (pipeline/vector.py:
frames may be partially filled) — a flushed tail frame pads with
all-zero INVALID slots the pipeline ignores, so padding never touches
session state or per-packet counters.

Conservation extends the steering identity downward::

    offered == sum(steered) + fenced + no_owner          (steering)
    steered[i] == delivered[i] + queue_drops[i] + pending[i]  (here)

``pending`` (buffered + queued) drains to zero on ``stop()``, so after
a quiesce the end-to-end identity
``offered == sum(delivered) + attributed drops`` holds EXACTLY —
the live-rebalance bench asserts it packet-for-packet.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Dict, List, Optional

import numpy as np

log = logging.getLogger("vpp_tpu.fleet")

# drop causes THIS layer attributes on top of the steering tier's
# STEER_DROP_CAUSES (queue overflow / failed frame — both counted
# against offered). The --counters parity pass checks the collector's
# cause axis is exactly the union.
QUEUE_DROP_CAUSES = ("queue",)


class FleetPump:
    """Queue-fronted fan-out of packed frames to fleet instances."""

    def __init__(self, steering, frame_width: int = 256,
                 queue_slots: int = 64, with_aux: bool = True):
        self.steering = steering
        self.frame_width = int(frame_width)
        self.with_aux = bool(with_aux)
        self._names: List[str] = sorted(steering.instances)
        self._queues: Dict[str, queue.Queue] = {
            n: queue.Queue(maxsize=int(queue_slots))
            for n in self._names}
        self._lock = threading.Lock()
        # dispatch-side per-instance packet buffers (columns pending
        # re-framing at frame_width)
        self._buf: Dict[str, List[np.ndarray]] = {
            n: [] for n in self._names}
        self._buffered: Dict[str, int] = {n: 0 for n in self._names}
        self._submitted: Dict[str, int] = {n: 0 for n in self._names}
        # pump-local conservation terms: the steering tier's stats are
        # cumulative across ITS lifetime (it may front many pumps), so
        # the per-pump identity accounts its own offered/drops
        self._offered = 0
        self._steer_drops: Dict[str, int] = {"fenced": 0,
                                             "no_owner": 0}
        self._delivered: Dict[str, int] = {n: 0 for n in self._names}
        self._queue_drops: Dict[str, int] = {n: 0 for n in self._names}
        self._aux: Dict[str, Optional[np.ndarray]] = {
            n: None for n in self._names}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # --- lifecycle ---------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()
        for name in self._names:
            t = threading.Thread(target=self._worker, args=(name,),
                                 name=f"fleet-pump-{name}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self, drain: bool = True) -> None:
        """Quiesce: flush partial buffers, drain queues (unless
        ``drain=False``), join workers."""
        if drain:
            self.flush()
            for q in self._queues.values():
                q.join()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []

    # --- dispatch side ----------------------------------------------

    def submit(self, flat: np.ndarray, **steer_kw: Any) -> None:
        """Steer one packed ``[5, B]`` frame; full native-width frames
        are enqueued immediately, the remainder buffers until the next
        submit or :meth:`flush`."""
        flat = np.asarray(flat, np.int32)
        groups, drops = self.steering.partition(flat, **steer_kw)
        with self._lock:
            self._offered += int(flat.shape[1])
            self._steer_drops["fenced"] += drops["fenced"]
            self._steer_drops["no_owner"] += drops["no_owner"]
            for name, idx in groups.items():
                self._buf[name].append(flat[:, idx])
                self._buffered[name] += int(idx.size)
                self._drain_buffer_locked(name, pad_tail=False)

    def flush(self) -> None:
        """Emit every buffered partial frame, padded with invalid
        slots to the native width."""
        with self._lock:
            for name in self._names:
                self._drain_buffer_locked(name, pad_tail=True)

    def _drain_buffer_locked(self, name: str, pad_tail: bool) -> None:
        w = self.frame_width
        while self._buffered[name] >= w or (pad_tail
                                            and self._buffered[name]):
            cols = np.concatenate(self._buf[name], axis=1)
            frame, rest = cols[:, :w], cols[:, w:]
            n_real = int(frame.shape[1])
            if n_real < w:
                pad = np.zeros((5, w - n_real), np.int32)
                frame = np.concatenate([frame, pad], axis=1)
            self._buf[name] = [rest] if rest.shape[1] else []
            self._buffered[name] -= n_real
            try:
                self._queues[name].put_nowait((frame, n_real))
                self._submitted[name] += n_real
            except queue.Full:
                # attributed, never silent: the conservation identity
                # counts these against offered
                self._queue_drops[name] += n_real

    # --- worker side -------------------------------------------------

    def _worker(self, name: str) -> None:
        dp = self.steering.instances[name]
        q = self._queues[name]
        while True:
            try:
                frame, n_real = q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                res = dp.process_packed(frame, commit=True,
                                        with_aux=self.with_aux)
                aux = (np.asarray(res[1]).astype(np.int64)
                       if self.with_aux else None)
                with self._lock:
                    self._delivered[name] += n_real
                    if aux is not None:
                        prev = self._aux[name]
                        self._aux[name] = (aux if prev is None
                                           else prev + aux)
            except Exception:
                log.exception("fleet worker %s: frame failed "
                              "(%d packets dropped, attributed)",
                              name, n_real)
                with self._lock:
                    self._queue_drops[name] += n_real
            finally:
                q.task_done()

    # --- observability ----------------------------------------------

    def pending(self) -> int:
        with self._lock:
            buffered = sum(self._buffered.values())
            queued = sum(self._submitted[n] - self._delivered[n]
                         for n in self._names)
        return buffered + queued

    def stats_snapshot(self) -> Dict[str, Any]:
        from vpp_tpu.pipeline.dataplane import PACKED_AUX_SCHEMA

        with self._lock:
            out: Dict[str, Any] = {
                "submitted": dict(self._submitted),
                "delivered": dict(self._delivered),
                "queue_drops": dict(self._queue_drops),
                "buffered": dict(self._buffered),
                "aux": {},
            }
            for name, aux in self._aux.items():
                if aux is not None:
                    out["aux"][name] = {
                        k: int(aux[i])
                        for i, k in enumerate(PACKED_AUX_SCHEMA)}
        return out

    def conservation(self) -> Dict[str, int]:
        """End-to-end identity terms (exact after ``stop()``):
        ``offered == delivered + fenced + no_owner + queue_drops
        + pending``. All terms are THIS pump's own accounting — the
        steering tier's cumulative stats span its whole lifetime."""
        with self._lock:
            return {
                "offered": self._offered,
                "delivered": sum(self._delivered.values()),
                "fenced_drops": self._steer_drops["fenced"],
                "no_owner_drops": self._steer_drops["no_owner"],
                "queue_drops": sum(self._queue_drops.values()),
                "pending": (sum(self._buffered.values())
                            + sum(self._submitted[n]
                                  - self._delivered[n]
                                  for n in self._names)),
            }
