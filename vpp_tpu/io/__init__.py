"""Packet-IO front-end: transports, IO daemon, and the dataplane pump.

The piece the reference gets from VPP's input/output graph nodes plus
its DPDK/AF_PACKET/TAP drivers (contiv-vswitch.conf:8-11, graph nodes in
docs/VPP_PACKET_TRACING_K8S.md:28-50): real packets in from the wire,
through the native codec into shared-memory frame rings, across the
jitted TPU pipeline, and back out rewritten.

  wire -> Transport.recv -> PacketCodec.parse -> rx IORing
       -> DataplanePump -> Dataplane.process (TPU) -> tx IORing
       -> PacketCodec.rewrite (+ VXLAN encap) -> Transport.send -> wire
"""

from vpp_tpu.io.rings import IORing, IORingPair
from vpp_tpu.io.transport import (
    AfPacketTransport,
    SocketPairTransport,
    TapTransport,
    Transport,
)
from vpp_tpu.io.daemon import IODaemon
from vpp_tpu.io.governor import LatencyGovernor, PriorityFilter
from vpp_tpu.io.pump import DataplanePump

__all__ = [
    "IORing", "IORingPair", "Transport", "AfPacketTransport",
    "TapTransport", "SocketPairTransport", "IODaemon", "DataplanePump",
    "LatencyGovernor", "PriorityFilter",
]
