"""Frame rings with payload blocks: header columns + raw packet bytes.

The SPSC frame ring (native/frame_ring.cpp) carries the 12 SoA header
columns; full packet bytes travel in a payload block — a [n_slots, VEC,
snap] uint8 region indexed by the same slot number, synchronized by the
ring's head/tail (the slot's payload is owned by whoever owns the slot).
This mirrors VPP's split between vlib frame vectors and buffer memory.

Both sides can live in one process (bytearray buffers, tests/dev) or in
two (multiprocessing.shared_memory, the production daemon split).
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Optional, Tuple

import numpy as np

from vpp_tpu.native.ring import FrameRing

VEC = 256
DEFAULT_SNAP = 2048
DEFAULT_SLOTS = 64

# Rows of one packed descriptor slot — MUST equal
# pipeline.dataplane.PACKED_IN_ROWS (20 B/packet bit-packed layout).
# Duplicated here rather than imported: this module is shared with the
# IO daemon process, which must stay jax-free (pipeline.dataplane pulls
# in jax at import). pipeline/persistent.py asserts the two agree.
DESC_ROWS = 5

DEFAULT_RING_SLOTS = 8
DEFAULT_RING_WINDOWS = 2


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def validate_ring_geometry(slots: int, windows: int) -> None:
    """Fail FAST on device-ring misconfiguration — called at YAML load
    (cmd/config.py) and at DeviceDescRing construction, so a bad knob
    is rejected with a clear message when the config is read, not at
    the first persistent-mode pump launch (the PR 6
    validate_dataplane_config pattern)."""
    if not _is_pow2(int(slots)):
        raise ValueError(
            f"io_ring_slots must be a power of two, got {slots}")
    if not _is_pow2(int(windows)) or int(windows) < 2:
        raise ValueError(
            f"io_ring_windows must be a power of two >= 2 "
            f"(double buffer), got {windows}")


class DeviceDescRing:
    """Host half of the device-resident descriptor rings (ISSUE 7).

    ``windows`` pinned staging buffers of ``slots`` descriptor slots
    each ([slots, DESC_ROWS, batch] int32, ~20 B/packet — the packed
    pipeline boundary), cycled in strict ring order: ``acquire()``
    hands out the next window for staging, ``release()`` returns it
    once its transfer (and the paired tx-ring fetch) completed. With
    the default double buffer, the pump stages + dispatches window
    N+1 while window N's results are still being fetched — the upload
    of the next refill and the writeback of the previous window
    overlap, which is what makes the steady state one exchange per
    window instead of two blocking callbacks per frame.

    Geometry is config-static (``io.io_ring_slots`` /
    ``io.io_ring_windows``): ``slots`` is part of the device program's
    jit-cache key the way ``sess_ways`` is carried in the session
    arrays' shape, so geometry never retraces at runtime.

    Thread contract: ONE stager calls acquire(), one fetcher calls
    release() — the cyclic cursor + per-window state are guarded by a
    condition variable, so a release landing concurrently with the
    stager blocking in acquire() wakes it exactly once (the
    double-buffer swap test races these on purpose).
    """

    def __init__(self, slots: int = DEFAULT_RING_SLOTS, batch: int = VEC,
                 windows: int = DEFAULT_RING_WINDOWS):
        validate_ring_geometry(slots, windows)
        self.slots = int(slots)
        self.batch = int(batch)
        self.windows = int(windows)
        self._desc = [np.zeros((self.slots, DESC_ROWS, self.batch),
                               np.int32) for _ in range(self.windows)]
        self._now = [np.zeros(self.slots, np.int32)
                     for _ in range(self.windows)]
        # the spare descriptor lane (ISSUE 11): per-slot rx-enqueue
        # microsecond stamps the window program turns into wire-latency
        # histogram samples (0 = unstamped; telemetry off leaves the
        # lane zero — 4 B/slot, not worth gating the allocation)
        self._stamp = [np.zeros(self.slots, np.int32)
                       for _ in range(self.windows)]
        self._held = [False] * self.windows
        self._next = 0  # cyclic acquire cursor
        self._cv = threading.Condition(threading.Lock())
        # per-window fill occupancy (ISSUE 13): how many slots each
        # shipped window actually carried — the latency governor's
        # occupancy input (lone windows mean shrinking the fill cap
        # cannot lower p99 any further) and the `show governor` /
        # `show io` fill telemetry. note_fill() is called by the
        # stager at dispatch; readers take consistent (windows, slots)
        # pairs via fill_snapshot().
        self._fill_windows = 0
        self._fill_slots = 0

    def note_fill(self, n_slots: int) -> None:
        """Record one shipped window's slot occupancy."""
        with self._cv:
            self._fill_windows += 1
            self._fill_slots += int(n_slots)

    def fill_snapshot(self) -> Tuple[int, int]:
        """``(windows_shipped, slots_filled)`` cumulative — callers
        delta between reads for a recent-window average fill."""
        with self._cv:
            return self._fill_windows, self._fill_slots

    def window_bytes(self) -> int:
        """Descriptor bytes one window ships each way (the window-math
        numerator of docs/IO_PATH.md)."""
        return self._desc[0].nbytes

    def acquire(self, timeout: Optional[float] = None):
        """The next staging window in cyclic order, or None on timeout
        (every earlier window still in flight — host-side
        backpressure). Returns ``(widx, desc, now, stamp)`` views
        (``stamp`` is the per-slot rx-enqueue µs lane); the caller
        owns them until ``release(widx)``."""
        with self._cv:
            w = self._next
            if not self._cv.wait_for(lambda: not self._held[w],
                                     timeout=timeout):
                return None
            self._held[w] = True
            self._next = (w + 1) % self.windows
            return w, self._desc[w], self._now[w], self._stamp[w]

    def release(self, widx: int) -> None:
        """Window transfer complete — buffer reusable. Any-order safe
        (the fetcher releases in dispatch order, but a shutdown path
        may release a window it never dispatched)."""
        with self._cv:
            if not self._held[widx]:
                raise RuntimeError(
                    f"device-ring window {widx} released while free")
            self._held[widx] = False
            self._cv.notify_all()

    def in_flight(self) -> int:
        """Windows currently held (staged or awaiting writeback)."""
        with self._cv:
            return sum(self._held)


class Frame(NamedTuple):
    cols: Dict[str, np.ndarray]   # 12 ring columns, [VEC]
    n: int                        # valid packet count
    epoch: int
    payload: np.ndarray           # uint8 [VEC, snap] view for this slot


class IORing:
    """A FrameRing plus its payload block (one direction)."""

    def __init__(self, ring_buf, payload_buf, n_slots: int = DEFAULT_SLOTS,
                 snap: int = DEFAULT_SNAP, create: bool = True):
        self.ring = FrameRing(ring_buf, n_slots=n_slots, create=create)
        n_slots = self.ring.n_slots
        self.snap = snap
        need = n_slots * VEC * snap
        mv = memoryview(payload_buf)
        if len(mv) < need:
            raise ValueError(f"payload buffer too small: {len(mv)} < {need}")
        self.payload = np.frombuffer(mv, np.uint8, count=need).reshape(
            n_slots, VEC, snap
        )
        lib = self.ring.lib
        self._hdr_size = int(lib.fr_header_size())
        self._slot_size = int(lib.fr_slot_size())

    @classmethod
    def required_sizes(cls, n_slots: int = DEFAULT_SLOTS,
                       snap: int = DEFAULT_SNAP) -> Tuple[int, int]:
        return FrameRing.required_size(n_slots), n_slots * VEC * snap

    def _slot_index(self, off: int) -> int:
        return (off - self._hdr_size) // self._slot_size

    # --- producer ---
    def push(self, cols: Dict[str, np.ndarray], n: int,
             payload: Optional[np.ndarray] = None, epoch: int = 0) -> bool:
        """Write one frame (+payload rows) — False if full.

        Payload rows are copied only up to the frame's max wire length
        (pkt_len + ethernet header), not the full snap width: consumers
        never read past wire_len per packet, and copying snap bytes per
        row (512 KB/frame at snap 2048) would bottleneck the host path
        on memcpy for small-packet traffic."""
        off = self.ring.reserve()
        if off < 0:
            return False
        if payload is not None:
            w = self.snap
            if n and "pkt_len" in cols:
                w = min(self.snap, int(np.max(cols["pkt_len"][:n])) + 14)
            self.payload[self._slot_index(off), :n, :w] = payload[:n, :w]
        self.ring.write_slot(off, cols, n, epoch)
        self.ring.commit()
        return True

    def push_packed(self, packed: np.ndarray, poff: int, n: int,
                    rx_frame: Frame, host_if: int, epoch: int,
                    cause: np.ndarray) -> bool:
        """Fast-path producer: decode packed device results
        ([5, bucket] int32, columns [poff, poff+n)) STRAIGHT into the
        reserved slot's column block in one native call (pass-through
        columns from the rx slot, non-IPv4 re-punted to ``host_if``),
        then copy the payload rows. Per-packet drop_cause lands in
        ``cause`` (int32[VEC]) for the caller. False if full."""
        from vpp_tpu.native.pktio import unpack_to_slot

        ring = self.ring
        off = ring.reserve()
        if off < 0:
            return False
        hdr = np.frombuffer(ring._mv, np.uint32, count=2, offset=off)
        hdr[0] = n
        hdr[1] = epoch
        base = ring._arr.ctypes.data
        unpack_to_slot(
            packed, poff, n,
            rx_frame.cols["src_ip"].ctypes.data,
            base + off + ring._slot_hdr, host_if, cause,
        )
        if rx_frame.payload is not None:
            w = self.snap
            if n:
                w = min(self.snap,
                        int(np.max(rx_frame.cols["pkt_len"][:n])) + 14)
            self.payload[self._slot_index(off), :n, :w] = \
                rx_frame.payload[:n, :w]
        ring.commit()
        return True

    # --- consumer ---
    def peek(self) -> Optional[Frame]:
        """Zero-copy views of the oldest frame (cols + payload), or None.
        Valid until release()."""
        lib, base = self.ring.lib, self.ring._base
        off = lib.fr_consume_peek(base)
        if off < 0:
            return None
        idx = self._slot_index(off)
        hdr = np.frombuffer(self.ring._mv, np.uint32, count=2, offset=off)
        return Frame(
            self.ring._slot_views(off), int(hdr[0]), int(hdr[1]),
            self.payload[idx],
        )

    def peek_nth(self, k: int) -> Optional[Frame]:
        """Zero-copy views of the k-th oldest pending frame (k=0 ==
        peek()), or None if fewer than k+1 frames are committed. The
        slot stays ring-owned until k+1 release() calls happen, so the
        views are stable while the frame is in flight on the device."""
        lib, base = self.ring.lib, self.ring._base
        off = lib.fr_consume_peek_nth(base, k)
        if off < 0:
            return None
        idx = self._slot_index(off)
        hdr = np.frombuffer(self.ring._mv, np.uint32, count=2, offset=off)
        return Frame(
            self.ring._slot_views(off), int(hdr[0]), int(hdr[1]),
            self.payload[idx],
        )

    def release(self) -> None:
        self.ring.release()

    def pending(self) -> int:
        return self.ring.pending()


class IORingPair:
    """rx + tx rings over in-process buffers or named shared memory."""

    def __init__(self, n_slots: int = DEFAULT_SLOTS, snap: int = DEFAULT_SNAP,
                 shm_name: Optional[str] = None, create: bool = True):
        ring_sz, pay_sz = IORing.required_sizes(n_slots, snap)
        self._shm = None
        self._views: list = []
        if shm_name is None:
            bufs = [bytearray(ring_sz), bytearray(pay_sz),
                    bytearray(ring_sz), bytearray(pay_sz)]
        else:
            from multiprocessing import shared_memory

            total = 2 * (ring_sz + pay_sz)
            if create:
                try:
                    self._shm = shared_memory.SharedMemory(
                        name=shm_name, create=True, size=total
                    )
                except FileExistsError:
                    # A crashed previous agent (kill -9 / OOM) leaves the
                    # segment behind; the restart must reclaim it, not
                    # fail to boot until an operator clears /dev/shm.
                    stale = shared_memory.SharedMemory(name=shm_name)
                    stale.close()
                    stale.unlink()
                    self._shm = shared_memory.SharedMemory(
                        name=shm_name, create=True, size=total
                    )
            else:
                self._shm = shared_memory.SharedMemory(name=shm_name)
            mv = self._shm.buf
            o = 0
            bufs = []
            for sz in (ring_sz, pay_sz, ring_sz, pay_sz):
                view = mv[o:o + sz]
                self._views.append(view)
                bufs.append(view)
                o += sz
        self.rx = IORing(bufs[0], bufs[1], n_slots, snap, create=create)
        self.tx = IORing(bufs[2], bufs[3], n_slots, snap, create=create)

    def close(self, unlink: bool = False) -> None:
        # Numpy arrays + memoryview slices into the shm buffer must all
        # be dropped before SharedMemory.close() (it refuses while
        # exported pointers exist); anything still pinned is reclaimed at
        # process exit, so failures here must not mask real errors.
        import gc

        for ring in (self.rx, self.tx):
            if ring is not None:
                ring.payload = None
                ring.ring._arr = None
                ring.ring._mv = None
                ring.ring._base = None
        self.rx = self.tx = None
        gc.collect()
        if self._shm is not None:
            for v in self._views:
                try:
                    v.release()
                except BufferError:
                    pass
            self._views.clear()
            try:
                self._shm.close()
            except BufferError:
                pass
            if unlink:
                try:
                    self._shm.unlink()
                except FileNotFoundError:
                    pass
            self._shm = None
