"""IO-daemon control channel: runtime attach/detach of packet endpoints.

The r2 daemon's transport set was fixed at process start (a pod added at
runtime could never send or receive a real packet — VERDICT r2 Missing
#1). This unix-socket JSON-line RPC lets the agent drive the daemon the
way the reference's CNI server drives VPP interface creation over the
binary API (plugins/contiv/remote_cni_server.go:895-1250):

  attach   {if_idx, kind, arg}   create a transport (afpacket|tap|fd)
                                 and plug it in as interface if_idx
  detach   {if_idx}              unplug + close the transport
  set_mac  {ip, mac}             static (ip → MAC) entry — the analog of
                                 the reference's configured static ARPs
                                 (pod.go:375-452), replacing broadcast-
                                 flood fallback for known pods
  del_mac  {ip}                  unpin a static entry (interface gone):
                                 it becomes evictable like a learned one
  stats    {}                    daemon counters
  list     {}                    current interface table
  neighbors {}                   (ip → MAC) table dump (show ip arp)

One request per connection, newline-delimited JSON — same wire shape as
the CNI shim transport (cni/transport.py), so the protocol layer is
shared.
"""

from __future__ import annotations

import logging
from typing import Optional

from vpp_tpu.cni.transport import CNITransportServer, cni_call

log = logging.getLogger("io_control")


class IOControlServer:
    """Control endpoint living inside the IO daemon process."""

    def __init__(self, daemon, socket_path: str):
        self.daemon = daemon
        self.socket_path = socket_path
        self._server = CNITransportServer(socket_path, self._dispatch)

    def start(self) -> "IOControlServer":
        self._server.start()
        return self

    def close(self) -> None:
        self._server.close()

    def _dispatch(self, method: str, params: dict) -> dict:
        try:
            if method == "attach":
                self.daemon.attach(
                    int(params["if_idx"]), params["kind"], params["arg"]
                )
                return {"result": 0}
            if method == "detach":
                removed = self.daemon.detach(int(params["if_idx"]))
                return {"result": 0, "removed": bool(removed)}
            if method == "set_mac":
                displaced = self.daemon.set_static_mac(
                    int(params["ip"]), bytes.fromhex(params["mac"])
                )
                # displaced=True: installed, but another pod's pinned
                # entry was evicted (it lost its no-flood guarantee) —
                # the agent decides whether to re-install that pod's ARP
                return {"result": 0, "displaced": bool(displaced)}
            if method == "del_mac":
                found = self.daemon.del_static_mac(int(params["ip"]))
                return {"result": 0, "found": bool(found)}
            if method == "stats":
                return {"result": 0, "stats": dict(self.daemon.stats)}
            if method == "neighbors":
                return {
                    "result": 0,
                    "neighbors": [
                        {"ip": ip, "mac": mac.hex(), "pin": pin}
                        for ip, mac, pin in self.daemon.mac.entries()
                    ],
                }
            if method == "list":
                return {
                    "result": 0,
                    "interfaces": {
                        str(idx): t.name
                        for idx, t in self.daemon.transports.items()
                    },
                }
            return {"result": 1, "error": f"unknown method {method!r}"}
        except Exception as e:  # noqa: BLE001 — fault isolation per request
            log.exception("control %s failed", method)
            return {"result": 1, "error": f"{type(e).__name__}: {e}"}


class IOControlClient:
    """Agent-side handle on a running IO daemon."""

    def __init__(self, socket_path: str, timeout: float = 10.0):
        self.socket_path = socket_path
        self.timeout = timeout

    def _call(self, method: str, params: Optional[dict] = None) -> dict:
        reply = cni_call(self.socket_path, method, params or {},
                         timeout=self.timeout)
        if reply.get("result") != 0:
            raise RuntimeError(
                f"io-daemon {method} failed: {reply.get('error')}"
            )
        return reply

    def attach(self, if_idx: int, kind: str, arg: str) -> None:
        self._call("attach", {"if_idx": if_idx, "kind": kind, "arg": arg})

    def detach(self, if_idx: int) -> bool:
        return bool(self._call("detach", {"if_idx": if_idx})["removed"])

    def set_mac(self, ip: int, mac: bytes) -> bool:
        """Install a static neighbor entry. True = installed but a
        DIFFERENT pod's pinned entry was displaced (pin pressure) —
        that pod lost its no-flood guarantee."""
        reply = self._call("set_mac", {"ip": ip, "mac": mac.hex()})
        return bool(reply.get("displaced"))

    def del_mac(self, ip: int) -> bool:
        """Unpin a static neighbor entry (interface unwired). True if
        an entry for ip existed."""
        return bool(self._call("del_mac", {"ip": ip})["found"])

    def stats(self) -> dict:
        return self._call("stats")["stats"]

    def neighbors(self) -> list:
        """The daemon's (ip → MAC) neighbor table: list of
        (ip, mac_bytes, pinned) — `show ip arp` analog data."""
        return [
            (int(e["ip"]), bytes.fromhex(e["mac"]), bool(e["pin"]))
            for e in self._call("neighbors")["neighbors"]
        ]

    def list_interfaces(self) -> dict:
        return {int(k): v
                for k, v in self._call("list")["interfaces"].items()}

    def ping(self) -> bool:
        try:
            self.stats()
            return True
        except (OSError, RuntimeError):
            return False
