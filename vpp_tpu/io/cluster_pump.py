"""ClusterPump: real wire traffic through the multi-chip fabric.

The mesh-mode analog of io/pump.DataplanePump: one pump drives N
per-node ring pairs against ONE ClusterDataplane. Each step gathers up
to MAX_FRAMES rx frames per node, stacks headers ([N, P] columns) and
packet bytes ([N, P, snap] uint8), runs ``cluster.step_wire`` — two
fused pipeline passes joined by all_to_all collectives carrying
headers AND payload — and writes BOTH result streams back out:

  * pass-1 ``local`` results to the INGRESS node's tx ring (locally
    delivered / host-punted / VXLAN-edge traffic; payload from the
    node's own rx slot, zero-copy as in the single-node pump);
  * pass-2 ``delivered`` results to the DESTINATION node's tx ring —
    the packet bytes arrive from the device (they crossed the fabric),
    so cross-node traffic needs no host-side source lookup at all.

PIPELINED (two stages, like the single-node pump's dispatch/writer
split): the dispatch thread stages + dispatches fabric steps without
waiting (session tables chain device-side; XLA queues the programs),
and the writer thread syncs results IN ORDER, writes the tx rings and
releases the rx slots. Frames stay ring-owned while in flight
(peek_nth + deferred release), so staging reads stable memory. On a
remote device this overlaps each step's ~RTT-sized sync with the next
step's staging + compute.

ICMP errors (io/icmp.py): attributed drops from either pass build
rate-limited error frames RE-INJECTED as that node's self-originated
ingress into a following step — the pipeline verdict returns them to
a local pod or back ACROSS the fabric toward a remote sender.

Reference analog: inter-node pod traffic through the VXLAN full-mesh
(plugins/contiv/node_events.go:184-250, two_node_two_pods.robot); here
the overlay is the ICI all_to_all and the per-node IO daemons only see
plain frames.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from vpp_tpu.io.rings import VEC, IORingPair
from vpp_tpu.pipeline.vector import Disposition, PacketVector

log = logging.getLogger("cluster-pump")

_PV_FIELDS = ("src_ip", "dst_ip", "proto", "sport", "dport", "ttl",
              "pkt_len", "rx_if", "flags")

_SENTINEL = object()

# per-node rx frames coalesced into one device step (two jit buckets:
# VEC and VEC*MAX_FRAMES packets per node, like the single-node pump's
# ladder — a backlog quadruples the per-step payload instead of paying
# a step per frame)
MAX_FRAMES = 4

# a synthetic frame re-injected into a following fabric step (ICMP
# error path); shape-compatible with the staging loop's ring Frames
_ErrFrame = collections.namedtuple("_ErrFrame", ("cols", "n", "payload"))


class ClusterPump:
    def __init__(self, cluster, ring_pairs: List[IORingPair],
                 poll_s: float = 0.0005, snap: Optional[int] = None,
                 depth: int = 2,
                 icmp_src_ips: Optional[List[int]] = None,
                 ingress_ifs: Optional[List[int]] = None,
                 max_inflight: Optional[int] = None):
        """``max_inflight`` (legacy alias ``depth``): fabric steps in
        flight before dispatch backpressures.
        ``icmp_src_ips``/``ingress_ifs`` (per mesh node:
        the pod gateway address and the node's host interface) enable
        the ICMP error path (see module doc)."""
        assert len(ring_pairs) == cluster.n_nodes
        self.cluster = cluster
        self.rings = ring_pairs
        self.poll_s = poll_s
        self.snap = snap or min(r.rx.snap for r in ring_pairs)
        self.depth = max(1, int(max_inflight if max_inflight is not None
                                else depth))
        self.max_inflight = self.depth
        self.icmp = None
        self._err_q: List[list] = [[] for _ in range(cluster.n_nodes)]
        self._err_lock = threading.Lock()
        if icmp_src_ips is not None:
            from vpp_tpu.io.icmp import IcmpErrorGen

            assert ingress_ifs is not None and \
                len(icmp_src_ips) == cluster.n_nodes
            self.icmp = [
                IcmpErrorGen(ip, VEC, self.snap) for ip in icmp_src_ips
            ]
            self.ingress_ifs = list(ingress_ifs)
            self._icmp_scratch = np.zeros((VEC, self.snap), np.uint8)
        # staging pool: dispatch cycles depth+2 buffer pairs per bucket
        # (allocated lazily per bucket) — a buffer is reused only after
        # its step completed in the writer, so a CPU-backend jnp.asarray
        # that aliases host memory can never observe a rewrite. Only
        # the flags row needs clearing between reuses — a stale VALID
        # flag would resurrect an old packet, while every other stale
        # column is inert behind flags=0.
        self._pool_n = self.depth + 2
        self._stage_pool: dict = {}
        # superset of DataplanePump's keys so the CLI's `show io`
        # renders either pump unchanged (batches == device steps)
        self.stats = {"steps": 0, "frames": 0, "pkts": 0,
                      "fabric_pkts": 0, "tx_ring_full": 0,
                      "batches": 0, "max_coalesce": 0, "batch_errors": 0,
                      # overlap observability, same contract as the
                      # single-node pump: fabric steps dispatched but
                      # not yet written, the wait for a step's results
                      # to become ready (overlapped with the next
                      # step's staging) vs the serial result copy
                      "inflight": 0, "inflight_peak": 0,
                      "t_fetch_wait": 0.0, "t_fetch": 0.0,
                      # two-tier dispatch telemetry, same contract as
                      # DataplanePump. Since ISSUE 12 the mesh step
                      # CAN take the classify-free kernel: the
                      # partition layer all-reduces the per-shard
                      # all-established flag, so the lax.cond
                      # predicate is SPMD-uniform and the fast tier
                      # runs under shard_map; fastpath_batches counts
                      # fabric steps where pass 1 dispatched fast on
                      # every node (from the step's own StepStats).
                      "fastpath_batches": 0, "fastpath_hits": 0,
                      "fastpath_alive": 0}
        self._step_lat = collections.deque(maxlen=2048)
        self._lat_lock = threading.Lock()
        # optional Prometheus Histogram (stats/collector.py set_pump):
        # same per-batch observation contract as DataplanePump, so
        # vpp_tpu_pump_batch_seconds carries data on mesh nodes too
        self.latency_hist = None
        # fast-tier histogram slot (set_pump parity): _write observes
        # fabric steps whose pass 1 dispatched classify-free on every
        # node (see the fastpath_batches comment above)
        self.fastpath_hist = None
        # frames peeked by dispatch but not yet released by the writer,
        # per ring (releases shift pending peek indices, so both sides
        # mutate under the lock — the single-node pump's held protocol)
        self._held = [0] * cluster.n_nodes
        self._held_lock = threading.Lock()
        self._inflight: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._seq = 0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # --- staging ---
    def _stage_buffers(self, p_cap: int):
        pool = self._stage_pool.get(p_cap)
        if pool is None:
            n = self.cluster.n_nodes
            pool = [
                (np.zeros((n, len(_PV_FIELDS), p_cap), np.int32),
                 np.zeros((n, p_cap, self.snap), np.uint8))
                for _ in range(self._pool_n)
            ]
            self._stage_pool[p_cap] = pool
        cols, payload = pool[self._seq % self._pool_n]
        cols[:, _PV_FIELDS.index("flags"), :] = 0
        return cols, payload

    def _pv_from(self, cols: np.ndarray):
        """[N, 9, P] int32 column block -> stacked PacketVector with
        EXACTLY the array construction the live path uses — warm() must
        produce the same jit signature or the first real frame pays a
        full recompile mid-traffic (minutes on a small host)."""
        import jax.numpy as jnp

        return PacketVector(**{
            name: jnp.asarray(cols[:, j]).view(
                jnp.uint32 if name in ("src_ip", "dst_ip") else jnp.int32
            )
            for j, name in enumerate(_PV_FIELDS)
        })

    def warm(self) -> None:
        """Compile the wire step at BOTH coalesce buckets before
        serving traffic (same input shapes/shardings as the live loop
        — a mid-traffic recompile costs minutes on a small host)."""
        import jax

        buckets = ((VEC,) if self.max_frames_per_ring <= 1
                   else (VEC, VEC * MAX_FRAMES))
        for p in buckets:
            cols, payload = self._stage_buffers(p)
            jax.block_until_ready(
                self.cluster.step_wire(self._pv_from(cols), payload,
                                       now=0)
            )

    # --- lifecycle ---
    # multi-host tick mode: the step is a COLLECTIVE, so an idle host
    # must still dispatch (empty staging) to pair with a peer that has
    # traffic — the tick driver, not this class, owns the cadence
    step_when_idle = False
    # multi-host tick mode: a swallowed staging/dispatch error would
    # desync the fleet's collective sequence SILENTLY (this host skips
    # a step its peers issued; their writers block forever). The tick
    # driver must see the exception and halt loudly.
    raise_on_error = False
    # multi-host tick mode: the coalesce bucket must be FLEET-AGREED —
    # p_cap derived from the LOCAL backlog would make hosts stage
    # different global shapes and issue mismatched collectives (gloo
    # aborts). 1 pins every host to the VEC bucket deterministically.
    max_frames_per_ring = MAX_FRAMES

    def start(self, dispatch: bool = True) -> "ClusterPump":
        """``dispatch=False``: writer thread only — an external tick
        driver calls ``_dispatch_once()`` itself (multi-host lockstep,
        where the fabric step must interleave deterministically with
        the driver's other collectives)."""
        loops = [(self._write_loop, "cluster-pump-tx")]
        if dispatch:
            loops.insert(0, (self._dispatch_loop, "cluster-pump-dispatch"))
        for fn, name in loops:
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def stop(self, join_timeout: Optional[float] = None) -> bool:
        self._stop.set()
        try:
            self._inflight.put_nowait(_SENTINEL)
        except queue.Full:
            pass  # writer drains; it checks _stop per item
        ok = True
        for t in self._threads:
            t.join(timeout=join_timeout)
            ok = ok and not t.is_alive()
        return ok

    def has_pending(self) -> bool:
        """Any un-dispatched rx frame (held ones excluded) or queued
        ICMP error — the multi-host idle-skip's local has-work signal.
        Owns the same locking the dispatch peek does."""
        with self._held_lock:
            for i, r in enumerate(self.rings):
                if r.rx.peek_nth(self._held[i]) is not None:
                    return True
        with self._err_lock:
            return any(self._err_q)

    # --- dispatch: rings -> device (async) ---
    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if not self._dispatch_once():
                    time.sleep(self.poll_s)
            except Exception:
                log.exception("cluster pump dispatch failed")
                self.stats["batch_errors"] += 1
                time.sleep(self.poll_s)

    def _dispatch_once(self) -> bool:
        n = self.cluster.n_nodes
        per_node: List[list] = []   # (frame, from_ring) pairs
        cap = self.max_frames_per_ring
        with self._err_lock:
            err_frames = [
                self._err_q[i][:cap] for i in range(n)
            ]
            for i in range(n):
                del self._err_q[i][:len(err_frames[i])]
        # the whole peek block holds _held_lock: a concurrent writer
        # release shifts pending peek indices, so a stale held snapshot
        # would skip one frame and double-take another (silent loss +
        # duplication) — same protocol as the single-node pump
        with self._held_lock:
            for i, r in enumerate(self.rings):
                lst = [(ef, False) for ef in err_frames[i]]
                taken = 0
                for k in range(cap - len(lst)):
                    f = r.rx.peek_nth(self._held[i] + k)
                    if f is None:
                        break
                    lst.append((f, True))
                    taken += 1
                self._held[i] += taken
                per_node.append(lst)
        if all(not lst for lst in per_node) and not self.step_when_idle:
            return False
        t0 = time.perf_counter()
        try:
            depth = max(len(lst) for lst in per_node)
            p_cap = VEC if depth <= 1 else VEC * MAX_FRAMES
            cols, payload = self._stage_buffers(p_cap)
            offs: List[list] = []  # per node: (offset, frame, from_ring)
            for i, lst in enumerate(per_node):
                off = 0
                node_offs = []
                for f, from_ring in lst:
                    for j, name in enumerate(_PV_FIELDS):
                        cols[i, j, off:off + f.n] = \
                            f.cols[name][:f.n].view(np.int32)
                    w = min(self.snap, f.payload.shape[1])
                    payload[i, off:off + f.n, :w] = f.payload[:f.n, :w]
                    if w < self.snap:
                        # a narrower source ring must not leave a
                        # previous step's bytes in the row tail —
                        # VALID rows ride the fabric full-width
                        payload[i, off:off + f.n, w:] = 0
                    node_offs.append((off, f, from_ring))
                    off += f.n
                offs.append(node_offs)
            result, deliv_pay = self.cluster.step_wire(
                self._pv_from(cols), payload
            )
            item = (result, deliv_pay, offs, t0)
        except Exception:
            # staging/dispatch failed AFTER taking frames: hand the
            # writer a failed item so ring releases stay in order and
            # the error frames are re-queued, not lost
            log.exception("cluster pump staging/dispatch failed")
            self.stats["batch_errors"] += 1
            item = (None, None,
                    [[(0, f, fr) for f, fr in lst]
                     for lst in per_node], t0)
            if self.raise_on_error:
                # ordered cleanup first, then surface: the lockstep
                # driver has no way to resync a fleet whose collective
                # sequences diverged
                with self._lat_lock:
                    self.stats["inflight"] += 1
                while True:
                    try:
                        self._inflight.put(item, timeout=0.05)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            with self._lat_lock:
                                self.stats["inflight"] -= 1
                            break
                raise
        # count the step in flight BEFORE the hand-off (the writer can
        # complete + decrement it the instant the put lands)
        with self._lat_lock:
            d = self.stats["inflight"] + 1
            self.stats["inflight"] = d
            if d > self.stats["inflight_peak"]:
                self.stats["inflight_peak"] = d
        while True:
            try:
                self._inflight.put(item, timeout=0.05)
                break
            except queue.Full:
                if self._stop.is_set():
                    # shutdown with a wedged writer: the runtime tears
                    # the rings down wholesale next — abandoning the
                    # held frames is safe, processing them is not
                    with self._lat_lock:
                        self.stats["inflight"] -= 1
                    return True
        self._seq += 1
        return True

    # --- writer: device -> rings, in order ---
    def _write_loop(self) -> None:
        while True:
            try:
                item = self._inflight.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            if item is _SENTINEL:
                return
            try:
                self._write(*item)
            except Exception:
                log.exception("cluster pump write failed")
                self.stats["batch_errors"] += 1
                self._release_item(item)
            finally:
                with self._lat_lock:
                    self.stats["inflight"] -= 1

    def _release_frames(self, offs) -> None:
        """Ordered ring releases + held decrements for one item (the
        single copy of the held protocol; success and failure paths
        both end here)."""
        for i, node_offs in enumerate(offs):
            with self._held_lock:
                for _, _, from_ring in node_offs:
                    if from_ring:
                        self.rings[i].rx.release()
                        self._held[i] -= 1

    def _release_item(self, item) -> None:
        """Failure path: release ring frames in order; error frames
        (destructively taken at dispatch) are re-queued ONLY when the
        device step never ran — a step that succeeded already injected
        them, and re-running would deliver duplicate ICMP errors."""
        result, _, offs, _ = item
        if result is None:
            for i, node_offs in enumerate(offs):
                requeue = [f for _, f, from_ring in node_offs
                           if not from_ring]
                if requeue:
                    with self._err_lock:
                        self._err_q[i][:0] = requeue
        self._release_frames(offs)

    def _write(self, result, deliv_pay, offs, t0) -> None:
        import jax

        if result is None:  # failed dispatch: ordered cleanup only
            self._release_item((None, None, offs, t0))
            return
        n = self.cluster.n_nodes
        # wait-for-ready apart from the copy: the wait overlaps the
        # dispatch thread's staging of the NEXT step (that's the whole
        # point of the depth), so only the copy is a serial cost
        tw0 = time.perf_counter()
        jax.block_until_ready((result.local, result.delivered, deliv_pay))
        tf0 = time.perf_counter()
        # the [N] sess_hits/rx/fastpath vectors ride the same fetch
        # group (a few bytes): the regime telemetry must not add a
        # round trip
        res_local, res_deliv, sess_hits, step_rx, step_fp = \
            jax.device_get(
                (result.local, result.delivered,
                 result.stats.sess_hits, result.stats.rx,
                 result.fastpath_pass1)
            )
        deliv_pay = np.asarray(jax.device_get(deliv_pay))
        tf1 = time.perf_counter()
        with self._lat_lock:
            self.stats["t_fetch_wait"] += tf0 - tw0
            self.stats["t_fetch"] += tf1 - tf0
            self.stats["fastpath_hits"] += int(np.asarray(sess_hits).sum())
            self.stats["fastpath_alive"] += int(np.asarray(step_rx).sum())
            # "a fast fabric step" = the INGRESS pass took the
            # classify-free tier on EVERY node (ISSUE 12: the
            # partition layer made the predicate SPMD-uniform; pass 2
            # is excluded — an empty fabric is vacuously fast)
            fast_step = bool(np.asarray(step_fp).min() >= 1)
            if fast_step:
                self.stats["fastpath_batches"] += 1

        # pass-1 results → ingress node's tx ring (payload: own rx slot)
        for i, node_offs in enumerate(offs):
            node_ids = np.asarray(res_local.node_id)[i]
            causes = np.asarray(res_local.drop_cause)[i]
            for off, f, from_ring in node_offs:
                out_cols = self._tx_cols(res_local, i, f.n, off=off)
                # fabric-consumed packets must not ALSO leave via the
                # ingress tx path: their disposition stays REMOTE with
                # a node_id >= 0; the daemon would VXLAN-encap
                # (next_hop) or uplink-send them. Mark them
                # transmitted-by-fabric (drop here, delivered at the
                # peer).
                fabric = (node_ids[off:off + f.n] >= 0) & \
                    (out_cols["disp"][:f.n] == int(Disposition.REMOTE))
                out_cols["disp"][:f.n] = np.where(
                    fabric, int(Disposition.DROP), out_cols["disp"][:f.n]
                )
                out_cols["flags"] = f.cols["flags"].copy()
                out_cols["meta"] = f.cols["meta"].copy()
                out_cols["proto"] = f.cols["proto"].copy()
                out_cols["pkt_len"] = f.cols["pkt_len"].copy()
                if self.rings[i].tx.push(out_cols, f.n,
                                         payload=f.payload,
                                         epoch=self.cluster.epoch):
                    self.stats["frames"] += 1
                    self.stats["pkts"] += f.n
                else:
                    self.stats["tx_ring_full"] += 1
                if self.icmp is not None:
                    self._queue_errors(i, f.cols, f.payload, f.n,
                                       causes[off:off + f.n])

        # pass-2 fabric deliveries → destination node's tx ring
        # (payload: the bytes that crossed the fabric)
        d_disp = np.asarray(res_deliv.disp)
        for i in range(n):
            live = np.nonzero(d_disp[i] != int(Disposition.DROP))[0]
            if not len(live):
                continue
            for start in range(0, len(live), VEC):
                sel = live[start:start + VEC]
                k = len(sel)
                out_cols = self._tx_cols(res_deliv, i, None, sel=sel)
                out_cols["flags"] = np.zeros(VEC, np.int32)
                out_cols["flags"][:k] = 1  # FLAG_VALID
                out_cols["meta"] = np.full(VEC, -1, np.int32)
                pay = np.zeros((VEC, self.snap), np.uint8)
                pay[:k] = deliv_pay[i][sel]
                if self.rings[i].tx.push(out_cols, k, payload=pay,
                                         epoch=self.cluster.epoch):
                    self.stats["frames"] += 1
                    self.stats["pkts"] += k
                    self.stats["fabric_pkts"] += k
                else:
                    self.stats["tx_ring_full"] += 1
        # drop attribution → ICMP errors, re-injected into a following
        # step. Pass-2 drops matter most here: the invoking packet came
        # from ANOTHER node, and the re-injected error's pipeline
        # verdict sends it back ACROSS THE FABRIC to that sender.
        if self.icmp is not None:
            from vpp_tpu.native.ring import RING_COLUMNS

            d_cause = np.asarray(res_deliv.drop_cause)
            d_pk = res_deliv.pkts
            width = d_cause.shape[1]
            for i in range(n):
                if not d_cause[i].any():
                    continue
                cols_like = {
                    name: np.zeros(width, dt) for name, dt in RING_COLUMNS
                }
                cols_like["src_ip"] = np.asarray(d_pk.src_ip)[i]
                cols_like["pkt_len"] = np.asarray(d_pk.pkt_len)[i]
                cols_like["ttl"] = np.asarray(d_pk.ttl)[i]
                cols_like["flags"] = np.asarray(d_pk.flags)[i]
                self._queue_errors(i, cols_like, deliv_pay[i], width,
                                   d_cause[i])
        self.stats["steps"] += 1
        self.stats["batches"] += 1
        self.stats["max_coalesce"] = max(
            self.stats["max_coalesce"],
            sum(len(node_offs) for node_offs in offs),
        )
        # ring releases LAST, after every read of the frames' memory:
        # an exception anywhere above leaves all releases to the
        # writer loop's _release_item (no double release possible)
        self._release_frames(offs)
        lat = time.perf_counter() - t0
        with self._lat_lock:
            self._step_lat.append(lat)
        if self.latency_hist is not None:
            self.latency_hist.observe(lat)
        # fast-tier slice of the distribution (DataplanePump parity):
        # only fabric steps whose ingress pass dispatched classify-free
        # on every node observe here
        if fast_step and self.fastpath_hist is not None:
            self.fastpath_hist.observe(lat)

    def _queue_errors(self, node: int, cols, payload, n: int,
                      causes: np.ndarray) -> None:
        """Build rate-limited ICMP errors for one frame's attributed
        drops and queue them for re-injection as the node's
        self-originated ingress in a following fabric step (produced by
        the writer thread, consumed by dispatch — under _err_lock)."""
        from vpp_tpu.io.icmp import classify_drops

        gen = self.icmp[node]
        idxs, types = classify_drops(causes, cols["flags"],
                                     cols["ttl"], n)
        if not len(idxs):
            return
        with self._err_lock:
            if len(self._err_q[node]) >= MAX_FRAMES:
                gen.suppressed += len(idxs)
                return
        built = gen.build_frame(
            idxs, types, cols, payload, self._icmp_scratch,
            rx_if=int(self.ingress_ifs[node]),
        )
        if built is None:
            return
        out_cols, k = built
        with self._err_lock:
            self._err_q[node].append(_ErrFrame(
                cols=out_cols, n=k, payload=self._icmp_scratch[:k].copy()
            ))
        self.stats["icmp_errors"] = self.stats.get("icmp_errors", 0) + k

    def latency_us(self) -> dict:
        """p50/p99 fabric-step latency (staged -> both tx streams
        written) over the recent window — `show io` renders this."""
        with self._lat_lock:
            snap = list(self._step_lat)
        if not snap:
            return {"p50": 0.0, "p99": 0.0, "n": 0}
        arr = np.asarray(snap) * 1e6
        return {
            "p50": float(np.percentile(arr, 50)),
            "p99": float(np.percentile(arr, 99)),
            "n": int(arr.size),
        }

    @staticmethod
    def _tx_cols(res, i: int, n: Optional[int], sel=None,
                 off: int = 0) -> dict:
        """TX ring columns from one node's row of a NodeTx result (tx
        direction: the rx_if column carries the egress interface).
        ``off`` slices a coalesced frame's packets out of the node
        row; ``sel`` gathers arbitrary positions (delivered path)."""
        pk = res.pkts
        out = {}

        def take(arr, dtype):
            a = np.asarray(arr)[i]
            col = np.zeros(VEC, dtype)
            if sel is not None:
                col[:len(sel)] = a[sel].astype(dtype, copy=False)
            else:
                col[:n] = a[off:off + n].astype(dtype, copy=False)
            return col

        out["src_ip"] = take(pk.src_ip, np.uint32)
        out["dst_ip"] = take(pk.dst_ip, np.uint32)
        out["proto"] = take(pk.proto, np.int32)
        out["sport"] = take(pk.sport, np.int32)
        out["dport"] = take(pk.dport, np.int32)
        out["ttl"] = take(pk.ttl, np.int32)
        out["pkt_len"] = take(pk.pkt_len, np.int32)
        out["rx_if"] = take(res.tx_if, np.int32)
        out["disp"] = take(res.disp, np.int32)
        out["next_hop"] = take(res.next_hop, np.uint32)
        return out
