"""IO daemon: pumps packets between transports and the frame rings.

The process that plays VPP's input/output nodes (af-packet-input →
ethernet-input on rx; ip4-rewrite → interface-output on tx): an rx
thread select()s across all transports, batch-parses raw frames through
the native codec into the rx ring; a tx thread drains the tx ring,
applies native header rewrite (NAT results, TTL, checksums), VXLAN-
encapsulates remote-bound packets toward their peer VTEP, and transmits
per disposition. Ethernet addressing uses learned (ip → MAC) mappings
from rx traffic with broadcast fallback — the ARP analog for the
directly-attached pod links the reference configures static ARP for
(plugins/contiv/pod.go:375-452).
"""

from __future__ import annotations

import logging
import select
import threading
import time
from typing import Dict, Optional

import numpy as np

from vpp_tpu.io.rings import IORingPair, VEC
from vpp_tpu.io.transport import Transport
from vpp_tpu.native.pktio import MacTable, PacketCodec

log = logging.getLogger("io_daemon")


class IODaemon:
    def __init__(
        self,
        rings: IORingPair,
        transports: Dict[int, Transport],
        uplink_if: int,
        host_if: Optional[int] = None,
        vtep_ip: int = 0,
        vni: int = 10,
        poll_s: float = 0.0002,
        rx_push_wait_s: float = 0.02,
    ):
        """``rx_push_wait_s``: how long a full rx ring backpressures
        the rx thread before the parsed batch is dropped. While the
        thread waits, later frames queue in the (64 MB-deep) kernel
        sockets instead of dying between the transport and the pump —
        a transient pump stall (jit ramp, GC, a chained fold draining)
        then costs queueing delay, not loss (the r5 persistent-mode
        goodput collapse). 0 restores drop-on-full."""
        self.rings = rings
        self.transports = dict(transports)
        self.uplink_if = uplink_if
        self.host_if = host_if
        self.vtep_ip = vtep_ip
        self.vni = vni
        self.poll_s = poll_s
        self.rx_push_wait_s = rx_push_wait_s
        self.codec = PacketCodec(snap=rings.rx.snap)
        self._scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        self._rx_lens = np.zeros(VEC, np.uint32)
        # native neighbor table: rx learning + static entries, consulted
        # inside the per-frame native calls (never per packet in Python)
        self.mac = MacTable()
        # VXLAN encap staging: outer headers add 50 bytes of headroom
        self._encap_scratch = np.zeros((VEC, rings.rx.snap + 64), np.uint8)
        self.stats = {
            "rx_frames": 0, "rx_pkts": 0, "rx_ring_full": 0,
            "rx_ring_waits": 0,
            # rx-ring overflow drops in PACKETS (rx_ring_full counts
            # frames): the rx_full cause of the pump drop accounting
            # (ISSUE 7 satellite — vpp_tpu_pump_drops_total{reason=})
            "drops_rx_full": 0,
            "tx_frames": 0, "tx_pkts": 0, "tx_drops": 0, "tx_punts": 0,
            "trunc_drops": 0, "vxlan_encap": 0, "vxlan_decap": 0,
        }
        self._stop = threading.Event()
        self._threads = []

    # --- runtime interface management (driven by IOControlServer; the
    # reference analog is the CNI server creating pod TAP/veth
    # interfaces in the running vswitch, remote_cni_server.go:895-1250) ---
    def attach(self, if_idx: int, kind: str, arg: str) -> None:
        """Create a transport and plug it in as interface ``if_idx``.
        Replaces (and closes) any previous transport on that index —
        attach is idempotent for agent resync."""
        from vpp_tpu.io.transport import make_transport

        new = make_transport(kind, arg)
        old = self.transports.get(if_idx)
        self.transports[if_idx] = new  # dict assignment: GIL-atomic
        if old is not None:
            old.close()
        log.info("attached if %d: %s(%s)", if_idx, kind, arg)

    def detach(self, if_idx: int) -> bool:
        t = self.transports.pop(if_idx, None)
        if t is None:
            return False
        t.close()
        log.info("detached if %d (%s)", if_idx, t.name)
        return True

    def set_static_mac(self, ip: int, mac: bytes) -> bool:
        """Static (ip → MAC) entry — the reference's configured static
        ARP for pod links (pod.go:375-452); rx learning keeps it fresh
        but the first packet toward a silent pod no longer floods.
        Returns True when installing evicted ANOTHER pod's pinned entry
        (probe run fully pinned): that pod lost its no-flood guarantee,
        and the caller must surface the displacement, not treat the
        install as clean."""
        rc = self.mac.put(int(ip), bytes(mac))
        if not rc:
            # surfaced as an RPC error through the control socket: a
            # silently missing static means permanent broadcast flood
            raise RuntimeError("neighbor table rejected static entry")
        if rc == 2:
            log.warning(
                "static MAC for ip %#x displaced another pinned entry "
                "(neighbor table pin pressure)", ip,
            )
        return rc == 2

    def del_static_mac(self, ip: int) -> bool:
        """Unpin a static entry when its interface is unwired (CNI
        Delete / interconnect teardown). The entry becomes an ordinary
        learned entry — evictable, refreshable — instead of occupying
        pin-limited neighbor-table space for a dead interface. True if
        an entry existed."""
        return self.mac.unpin(int(ip))

    # --- lifecycle ---
    def start(self) -> "IODaemon":
        for fn, name in ((self._rx_loop, "io-rx"), (self._tx_loop, "io-tx")):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def stop(self, join_timeout: Optional[float] = None) -> bool:
        """Stop rx/tx threads; unbounded join by default — callers free
        the ring buffers next, so returning with a live thread would be
        a use-after-free into shared memory."""
        self._stop.set()
        ok = True
        for t in self._threads:
            t.join(timeout=join_timeout)
            ok = ok and not t.is_alive()
        return ok

    # --- rx: wire -> ring ---
    def _rx_loop(self) -> None:
        while not self._stop.is_set():
            # The control thread mutates transports at runtime
            # (attach/detach); a transport closed between the snapshot
            # and the select/recv surfaces as ValueError (fileno -1) or
            # OSError — both are routine during a CNI Delete and must
            # never kill the rx thread (that would silently stop ALL
            # packet reception on the node).
            try:
                fds = {t.fileno(): (if_idx, t)
                       for if_idx, t in list(self.transports.items())
                       if t.fileno() >= 0}
                if not fds:
                    time.sleep(0.05)
                    continue
                ready, _, _ = select.select(list(fds), [], [], 0.05)
                for fd in ready:
                    if_idx, transport = fds[fd]
                    bfd = transport.batch_fd
                    if bfd is not None:
                        # native fast path: recvmmsg straight into the
                        # payload scratch rows, zero bytes objects.
                        # Drain in a burst (bounded so one flooding
                        # interface can't starve the rest): a single
                        # batch per select wake caps rx at
                        # VEC / wake-latency and drops the rest in the
                        # kernel queue.
                        for _ in range(16):
                            n = self.codec.recv_batch(
                                bfd, self._scratch, self._rx_lens
                            )
                            if n <= 0:
                                break
                            self._ingest_scratch(if_idx, n)
                            if n < VEC:
                                break
                    else:
                        frames = transport.recv_frames(VEC)
                        if frames:
                            self._ingest(if_idx, frames)
            except (OSError, ValueError):
                continue
            except Exception:
                log.exception("rx iteration failed; continuing")

    def _ingest(self, if_idx: int, frames: list) -> None:
        if if_idx == self.uplink_if:
            # VXLAN datagrams from peer nodes carry the inner frame
            unwrapped = []
            for f in frames:
                off = self.codec.decap_offset(f, self.vni)
                if off:
                    self.stats["vxlan_decap"] += 1
                    unwrapped.append(f[off:])
                else:
                    unwrapped.append(f)
            frames = unwrapped
        for start in range(0, len(frames), VEC):
            chunk = frames[start:start + VEC]
            cols, n = self.codec.parse(chunk, if_idx, self._scratch)
            self.mac.learn(cols, self._scratch, n)
            if self._rx_push(cols, n):
                self.stats["rx_frames"] += 1
                self.stats["rx_pkts"] += n
            else:
                self.stats["rx_ring_full"] += 1
                self.stats["drops_rx_full"] += n

    def _ingest_scratch(self, if_idx: int, n: int) -> None:
        """Batch-received frames already sit in scratch rows: decap
        VXLAN on the uplink (in-row shift), parse in place, push."""
        lens = self._rx_lens
        if if_idx == self.uplink_if:
            self.stats["vxlan_decap"] += self.codec.decap_batch(
                self._scratch, lens, n, self.vni
            )
        cols, n = self.codec.parse_inplace(self._scratch, lens, n, if_idx)
        self.mac.learn(cols, self._scratch, n)
        if self._rx_push(cols, n):
            self.stats["rx_frames"] += 1
            self.stats["rx_pkts"] += n
        else:
            self.stats["rx_ring_full"] += 1
            self.stats["drops_rx_full"] += n

    def _rx_push(self, cols, n: int) -> bool:
        """Push one parsed frame, backpressuring briefly on a full
        ring (constructor doc). The retry sleeps at pump-poll
        granularity so a freed slot is taken within ~poll_s."""
        if self.rings.rx.push(cols, n, payload=self._scratch):
            return True
        deadline = time.monotonic() + self.rx_push_wait_s
        waited = False
        while time.monotonic() < deadline and not self._stop.is_set():
            waited = True
            time.sleep(self.poll_s)
            if self.rings.rx.push(cols, n, payload=self._scratch):
                if waited:
                    self.stats["rx_ring_waits"] += 1
                return True
        return False

    # --- tx: ring -> wire ---
    def _tx_loop(self) -> None:
        rings = self.rings
        while not self._stop.is_set():
            frame = rings.tx.peek()
            if frame is None:
                time.sleep(self.poll_s)
                continue
            try:
                self._transmit(frame)
            except Exception:
                log.exception("tx frame failed")
            rings.tx.release()
            self.stats["tx_frames"] += 1

    def _iface_arrays(self):
        """Snapshot the transport set into the parallel arrays the
        native dispatch consumes (if index, send fd, socket?, MAC).
        Transports mutate at runtime (attach/detach) so this is built
        per frame — a handful of entries, microseconds."""
        items = list(self.transports.items())
        idx = np.array([i for i, _ in items], np.int32)
        fds = np.zeros(len(items), np.int32)
        sock = np.zeros(len(items), np.uint8)
        macs = np.zeros((len(items), 6), np.uint8)
        for s, (_, t) in enumerate(items):
            bfd = t.batch_fd
            if bfd is not None:
                fds[s], sock[s] = bfd, 1
            else:
                # TAP char device: native path write()s per frame
                fds[s], sock[s] = t.fileno(), 0
            macs[s] = np.frombuffer(t.mac, np.uint8)
        return idx, fds, sock, macs

    def _transmit(self, frame) -> None:
        from vpp_tpu.native.pktio import flatten_cols

        cols, n, payload = frame.cols, frame.n, frame.payload
        # flatten the slot columns ONCE; rewrite + dispatch share it
        flat = flatten_cols(cols)
        # native rewrite: NAT/TTL results patched into the raw bytes with
        # checksum fixes (no-op for untouched packets)
        self.codec.rewrite(flat, payload, n)
        # native dispatch: policy checks, Ethernet addressing from the
        # neighbor table, per-egress batching and transmission in ONE
        # C pass — the per-packet Python loop it replaces capped the tx
        # path at ~0.34 Mpps; VPP runs this whole node in C per vector
        idx, fds, sock, macs = self._iface_arrays()
        counters, remote = self.codec.tx_dispatch(
            flat, payload, n, idx, fds, sock, macs,
            self.uplink_if,
            self.host_if if self.host_if is not None else -2,
            self.mac,
        )
        self.stats["tx_pkts"] += int(counters[0])
        self.stats["tx_drops"] += int(counters[1])
        self.stats["tx_punts"] += int(counters[2])
        self.stats["trunc_drops"] += int(counters[3])

        # REMOTE rows with a peer next-hop: batch VXLAN encap toward
        # the VTEPs + transmit, one native pass (vxlan-encap →
        # interface-output; inter-node traffic is a majority in real
        # clusters, so this path gets the same treatment as local tx)
        n_remote = int(counters[4])
        if n_remote:
            uplink = self.transports.get(self.uplink_if)
            if uplink is None:
                self.stats["tx_drops"] += n_remote
                return
            bfd = uplink.batch_fd
            sent = self.codec.encap_tx_batch(
                flat, payload, remote, n_remote,
                self.vtep_ip, self.vni, uplink.mac, self.mac,
                bfd if bfd is not None else uplink.fileno(),
                bfd is not None,
                self._encap_scratch,
            )
            self.stats["vxlan_encap"] += sent
            self.stats["tx_pkts"] += sent
            self.stats["tx_drops"] += n_remote - sent
