"""IO daemon: pumps packets between transports and the frame rings.

The process that plays VPP's input/output nodes (af-packet-input →
ethernet-input on rx; ip4-rewrite → interface-output on tx): an rx
thread select()s across all transports, batch-parses raw frames through
the native codec into the rx ring; a tx thread drains the tx ring,
applies native header rewrite (NAT results, TTL, checksums), VXLAN-
encapsulates remote-bound packets toward their peer VTEP, and transmits
per disposition. Ethernet addressing uses learned (ip → MAC) mappings
from rx traffic with broadcast fallback — the ARP analog for the
directly-attached pod links the reference configures static ARP for
(plugins/contiv/pod.go:375-452).
"""

from __future__ import annotations

import logging
import select
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from vpp_tpu.io.rings import IORingPair, VEC
from vpp_tpu.io.transport import BROADCAST_MAC, Transport
from vpp_tpu.native.pktio import (
    FLAG_NON_IP4,
    FLAG_TRUNC,
    FLAG_VALID,
    PacketCodec,
)
from vpp_tpu.pipeline.vector import Disposition

log = logging.getLogger("io_daemon")


class IODaemon:
    def __init__(
        self,
        rings: IORingPair,
        transports: Dict[int, Transport],
        uplink_if: int,
        host_if: Optional[int] = None,
        vtep_ip: int = 0,
        vni: int = 10,
        poll_s: float = 0.0002,
    ):
        self.rings = rings
        self.transports = dict(transports)
        self.uplink_if = uplink_if
        self.host_if = host_if
        self.vtep_ip = vtep_ip
        self.vni = vni
        self.poll_s = poll_s
        self.codec = PacketCodec(snap=rings.rx.snap)
        self._scratch = np.zeros((VEC, rings.rx.snap), np.uint8)
        self._rx_lens = np.zeros(VEC, np.uint32)
        self.mac_of: Dict[int, bytes] = {}
        self.stats = {
            "rx_frames": 0, "rx_pkts": 0, "rx_ring_full": 0,
            "tx_frames": 0, "tx_pkts": 0, "tx_drops": 0, "tx_punts": 0,
            "trunc_drops": 0, "vxlan_encap": 0, "vxlan_decap": 0,
        }
        self._stop = threading.Event()
        self._threads = []

    # --- runtime interface management (driven by IOControlServer; the
    # reference analog is the CNI server creating pod TAP/veth
    # interfaces in the running vswitch, remote_cni_server.go:895-1250) ---
    def attach(self, if_idx: int, kind: str, arg: str) -> None:
        """Create a transport and plug it in as interface ``if_idx``.
        Replaces (and closes) any previous transport on that index —
        attach is idempotent for agent resync."""
        from vpp_tpu.io.transport import make_transport

        new = make_transport(kind, arg)
        old = self.transports.get(if_idx)
        self.transports[if_idx] = new  # dict assignment: GIL-atomic
        if old is not None:
            old.close()
        log.info("attached if %d: %s(%s)", if_idx, kind, arg)

    def detach(self, if_idx: int) -> bool:
        t = self.transports.pop(if_idx, None)
        if t is None:
            return False
        t.close()
        log.info("detached if %d (%s)", if_idx, t.name)
        return True

    def set_static_mac(self, ip: int, mac: bytes) -> None:
        """Static (ip → MAC) entry — the reference's configured static
        ARP for pod links (pod.go:375-452); rx learning keeps it fresh
        but the first packet toward a silent pod no longer floods."""
        self.mac_of[int(ip)] = bytes(mac)

    # --- lifecycle ---
    def start(self) -> "IODaemon":
        for fn, name in ((self._rx_loop, "io-rx"), (self._tx_loop, "io-tx")):
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def stop(self, join_timeout: Optional[float] = None) -> bool:
        """Stop rx/tx threads; unbounded join by default — callers free
        the ring buffers next, so returning with a live thread would be
        a use-after-free into shared memory."""
        self._stop.set()
        ok = True
        for t in self._threads:
            t.join(timeout=join_timeout)
            ok = ok and not t.is_alive()
        return ok

    # --- rx: wire -> ring ---
    def _rx_loop(self) -> None:
        while not self._stop.is_set():
            # The control thread mutates transports at runtime
            # (attach/detach); a transport closed between the snapshot
            # and the select/recv surfaces as ValueError (fileno -1) or
            # OSError — both are routine during a CNI Delete and must
            # never kill the rx thread (that would silently stop ALL
            # packet reception on the node).
            try:
                fds = {t.fileno(): (if_idx, t)
                       for if_idx, t in list(self.transports.items())
                       if t.fileno() >= 0}
                if not fds:
                    time.sleep(0.05)
                    continue
                ready, _, _ = select.select(list(fds), [], [], 0.05)
                for fd in ready:
                    if_idx, transport = fds[fd]
                    bfd = transport.batch_fd
                    if bfd is not None:
                        # native fast path: recvmmsg straight into the
                        # payload scratch rows, zero bytes objects
                        n = self.codec.recv_batch(
                            bfd, self._scratch, self._rx_lens
                        )
                        if n > 0:
                            self._ingest_scratch(if_idx, n)
                    else:
                        frames = transport.recv_frames(VEC)
                        if frames:
                            self._ingest(if_idx, frames)
            except (OSError, ValueError):
                continue
            except Exception:
                log.exception("rx iteration failed; continuing")

    def _ingest(self, if_idx: int, frames: list) -> None:
        if if_idx == self.uplink_if:
            # VXLAN datagrams from peer nodes carry the inner frame
            unwrapped = []
            for f in frames:
                off = self.codec.decap_offset(f, self.vni)
                if off:
                    self.stats["vxlan_decap"] += 1
                    unwrapped.append(f[off:])
                else:
                    unwrapped.append(f)
            frames = unwrapped
        for start in range(0, len(frames), VEC):
            chunk = frames[start:start + VEC]
            cols, n = self.codec.parse(chunk, if_idx, self._scratch)
            self._learn_macs(chunk, cols, n)
            if self.rings.rx.push(cols, n, payload=self._scratch):
                self.stats["rx_frames"] += 1
                self.stats["rx_pkts"] += n
            else:
                self.stats["rx_ring_full"] += 1

    def _ingest_scratch(self, if_idx: int, n: int) -> None:
        """Batch-received frames already sit in scratch rows: decap
        VXLAN on the uplink (in-row shift), parse in place, push."""
        lens = self._rx_lens
        if if_idx == self.uplink_if:
            for i in range(n):
                row = self._scratch[i]
                off = self.codec.decap_offset(row[:lens[i]], self.vni)
                if off:
                    self.stats["vxlan_decap"] += 1
                    inner = int(lens[i]) - off
                    row[:inner] = row[off:lens[i]]
                    lens[i] = inner
        cols, n = self.codec.parse_inplace(self._scratch, lens, n, if_idx)
        self._learn_macs_scratch(cols, n)
        if self.rings.rx.push(cols, n, payload=self._scratch):
            self.stats["rx_frames"] += 1
            self.stats["rx_pkts"] += n
        else:
            self.stats["rx_ring_full"] += 1

    def _learn_macs(self, frames: list, cols: Dict[str, np.ndarray],
                    n: int) -> None:
        flags = cols["flags"]
        src = cols["src_ip"]
        for i in range(n):
            if flags[i] & FLAG_NON_IP4:
                continue
            self.mac_of[int(src[i])] = bytes(frames[i][6:12])

    def _learn_macs_scratch(self, cols: Dict[str, np.ndarray],
                            n: int) -> None:
        flags = cols["flags"]
        src = cols["src_ip"]
        for i in range(n):
            if flags[i] & FLAG_NON_IP4:
                continue
            self.mac_of[int(src[i])] = bytes(self._scratch[i, 6:12])

    # --- tx: ring -> wire ---
    def _tx_loop(self) -> None:
        rings = self.rings
        while not self._stop.is_set():
            frame = rings.tx.peek()
            if frame is None:
                time.sleep(self.poll_s)
                continue
            try:
                self._transmit(frame)
            except Exception:
                log.exception("tx frame failed")
            rings.tx.release()
            self.stats["tx_frames"] += 1

    def _transmit(self, frame) -> None:
        cols, n, payload = frame.cols, frame.n, frame.payload
        # native rewrite: NAT/TTL results patched into the raw bytes with
        # checksum fixes (no-op for untouched packets)
        self.codec.rewrite(cols, payload, n)
        flags = cols["flags"]
        disp = cols["disp"]
        tx_if = cols["rx_if"]     # tx direction: egress interface index
        dst_ip = cols["dst_ip"]
        next_hop = cols["next_hop"]
        pkt_len = cols["pkt_len"]
        uplink = self.transports.get(self.uplink_if)
        # per-egress-interface batches: the header patching stays a
        # (cheap) Python loop, the send syscalls are amortized through
        # sendmmsg (native/pkt_io.cpp pio_send_batch) — one syscall per
        # 64 frames instead of one per packet
        batches: Dict[int, Tuple[list, list]] = {}

        def enqueue(iface: int, row: int, wire_len: int) -> None:
            rows, lens = batches.setdefault(iface, ([], []))
            rows.append(row)
            lens.append(wire_len)

        for i in range(n):
            if not flags[i] & FLAG_VALID:
                continue
            if flags[i] & FLAG_TRUNC:
                # captured < claimed bytes: transmitting would pad with
                # residual slot data (cross-flow leak) or emit a frame
                # whose IP length lies — drop and make it visible
                self.stats["trunc_drops"] += 1
                continue
            d = int(disp[i])
            wire_len = min(int(pkt_len[i]) + 14, payload.shape[1])
            raw = payload[i, :wire_len]
            if d == int(Disposition.DROP):
                self.stats["tx_drops"] += 1
            elif d == int(Disposition.LOCAL):
                iface = int(tx_if[i])
                t = self.transports.get(iface)
                if t is None:
                    self.stats["tx_drops"] += 1
                    continue
                self._set_eth(raw, t.mac, int(dst_ip[i]))
                enqueue(iface, i, wire_len)
            elif d == int(Disposition.REMOTE):
                if uplink is None:
                    self.stats["tx_drops"] += 1
                    continue
                nh = int(next_hop[i])
                if nh:
                    wire = self.codec.encap(
                        payload[i], wire_len, self.vtep_ip, nh,
                        49152 + (int(dst_ip[i]) & 0x3FFF), self.vni,
                        uplink.mac, self.mac_of.get(nh, BROADCAST_MAC),
                    )
                    uplink.send_frame(wire)
                    self.stats["vxlan_encap"] += 1
                    self.stats["tx_pkts"] += 1
                else:
                    self._set_eth(raw, uplink.mac, int(dst_ip[i]))
                    enqueue(self.uplink_if, i, wire_len)
            elif d == int(Disposition.HOST):
                if self.host_if is None or \
                        self.host_if not in self.transports:
                    self.stats["tx_drops"] += 1
                    continue
                enqueue(self.host_if, i, wire_len)
            else:
                self.stats["tx_drops"] += 1

        for iface, (rows, lens) in batches.items():
            t = self.transports.get(iface)
            if t is None:
                self.stats["tx_drops"] += len(rows)
                continue
            punt = iface == self.host_if
            bfd = t.batch_fd
            if bfd is not None:
                sent = self.codec.send_batch(
                    bfd, payload, np.asarray(rows, np.uint32),
                    np.asarray(lens, np.uint32), len(rows),
                )
            else:
                sent = 0
                for row, ln in zip(rows, lens):
                    t.send_frame(payload[row, :ln].tobytes())
                    sent += 1
            self.stats["tx_punts" if punt else "tx_pkts"] += sent
            self.stats["tx_drops"] += len(rows) - sent

    def _set_eth(self, raw: np.ndarray, src_mac: bytes, dst_ip: int) -> None:
        if len(raw) < 14:
            return
        raw[0:6] = np.frombuffer(
            self.mac_of.get(dst_ip, BROADCAST_MAC), np.uint8
        )
        raw[6:12] = np.frombuffer(src_mac, np.uint8)
