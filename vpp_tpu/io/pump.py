"""DataplanePump: the agent-side thread bridging rings and the device.

Consumes rx-ring frames, lifts them into PacketVectors, runs the jitted
pipeline step on the device, and writes results (rewritten headers +
disposition + egress interface + peer next-hop) to the tx ring for the
IO daemon to serialize. Non-IPv4 frames bypass classification and are
punted to the host disposition (the STN punt analog for un-parseable
traffic, reference plugins/contiv/pod.go:375-381).

VERDICT r1 Missing #1: this is the pump that makes the data plane
reachable from real packets instead of synthetic vectors.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import jax
import numpy as np

from vpp_tpu.io.rings import IORingPair
from vpp_tpu.native.pktio import FLAG_NON_IP4, FLAG_TRUNC, FLAG_VALID
from vpp_tpu.pipeline.vector import Disposition, PacketVector

log = logging.getLogger("pump")


class DataplanePump:
    def __init__(self, dataplane, rings: IORingPair,
                 poll_s: float = 0.0002):
        self.dp = dataplane
        self.rings = rings
        self.poll_s = poll_s
        self.stats = {"frames": 0, "pkts": 0, "tx_ring_full": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DataplanePump":
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dp-pump"
        )
        self._thread.start()
        return self

    def stop(self, join_timeout: Optional[float] = None) -> bool:
        """Stop the pump; returns True when the thread has exited.

        Default join is unbounded: the caller tears the rings down right
        after, and a pump still inside dp.process (a first-frame jit
        compile easily exceeds seconds) must not race ring memory being
        freed — that's a use-after-free into shared memory."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=join_timeout)
            return not self._thread.is_alive()
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            frame = self.rings.rx.peek()
            if frame is None:
                time.sleep(self.poll_s)
                continue
            try:
                self._process(frame)
            except Exception:
                log.exception("pump frame failed")
            self.rings.rx.release()

    def _process(self, frame) -> None:
        cols = frame.cols
        flags = np.asarray(cols["flags"])
        non_ip = (flags & FLAG_NON_IP4) != 0
        trunc = (flags & FLAG_TRUNC) != 0
        # non-IPv4 and truncated slots are invalid for the pipeline
        # (bogus/partial headers); non-IP is punted after the step,
        # truncated is dropped by the daemon via its flag
        pv_flags = np.where(non_ip | trunc, 0, flags).astype(np.int32)
        pv = PacketVector(
            src_ip=np.asarray(cols["src_ip"]).copy(),
            dst_ip=np.asarray(cols["dst_ip"]).copy(),
            proto=np.asarray(cols["proto"]).copy(),
            sport=np.asarray(cols["sport"]).copy(),
            dport=np.asarray(cols["dport"]).copy(),
            ttl=np.asarray(cols["ttl"]).copy(),
            pkt_len=np.asarray(cols["pkt_len"]).copy(),
            rx_if=np.asarray(cols["rx_if"]).copy(),
            flags=pv_flags,
        )
        result = self.dp.process(pv)
        # one host transfer for everything the tx side needs
        out_pkts, disp, tx_if, next_hop = jax.device_get(
            (result.pkts, result.disp, result.tx_if, result.next_hop)
        )
        disp = np.asarray(disp).astype(np.int32).copy()
        tx_if = np.asarray(tx_if).astype(np.int32).copy()
        if non_ip.any():
            host_if = self.dp.host_if if self.dp.host_if is not None else -1
            disp[non_ip] = int(Disposition.HOST)
            tx_if[non_ip] = host_if
        out_cols = {
            "src_ip": np.asarray(out_pkts.src_ip),
            "dst_ip": np.asarray(out_pkts.dst_ip),
            "proto": np.asarray(out_pkts.proto),
            "sport": np.asarray(out_pkts.sport),
            "dport": np.asarray(out_pkts.dport),
            "ttl": np.asarray(out_pkts.ttl),
            "pkt_len": np.asarray(out_pkts.pkt_len),
            "rx_if": tx_if,            # tx direction: egress interface
            "flags": flags,            # original flags (valid + non-ip4)
            "disp": disp,
            "next_hop": np.asarray(next_hop),
            "meta": np.asarray(cols["meta"]),
        }
        if self.rings.tx.push(out_cols, frame.n, payload=frame.payload,
                              epoch=self.dp.epoch):
            self.stats["frames"] += 1
            self.stats["pkts"] += frame.n
        else:
            self.stats["tx_ring_full"] += 1
