"""DataplanePump: the agent-side bridge between frame rings and the device.

Staged pipeline with explicit depth (VERDICT r2 Next #2, then the r6
overlapped fetch ladder — BENCH_r05 pinned the deployed wire gap on
result fetch: ``io_daemon_t_fetch_s=5.65`` vs ``t_dispatch=0.237``):

  * the **dispatch** stage drains every pending rx frame, coalesces
    them by PACKET COUNT into device batches (VPP's own behavior:
    vector size grows under load), pads to a power-of-2 bucket so the
    jit cache stays small, and dispatches the packed single-transfer
    step WITHOUT waiting — JAX dispatch is asynchronous, and batches
    chain through the session tables device-side. Up to
    ``max_inflight`` dispatched batches ride concurrently before the
    stage backpressures;
  * the **adaptive chainer** engages when depth alone can't hide the
    round trip: backlog beyond one full ``max_batch`` bucket folds
    into a ``process_packed_chain`` K-stack — K packed batches in ONE
    device program (lax.scan), one dispatch + one fetch for K buckets
    of traffic. Light load never pays the chain's latency (a single
    frame still dispatches alone at the VEC bucket);
  * **fetch workers** (``fetch_workers``) pull finished batches and
    device_get them concurrently — on a remote device transport (the
    axon tunnel) a result fetch is a full RPC round trip (~80-130 ms
    measured), and round trips overlap across threads, so W workers
    divide the experienced fetch latency out of the throughput path.
    The stage timer splits ``t_fetch_wait`` (waiting for the device
    result to become ready — time hidden behind the other in-flight
    batches) from ``t_fetch`` (the result copy itself, the only
    serial cost);
  * the **tx writer** thread re-sequences completed batches back into
    dispatch order, splits them into ring frames, writes the tx ring
    (rewritten headers + disposition + egress interface + peer
    next-hop) and releases the rx slots — in order, as the SPSC ring
    requires. Session-state commit order is already serialized by the
    single dispatch thread, so only delivery needs the reorder buffer.

Frames stay ring-owned while in flight (fr_consume_peek_nth) — their
slot views and payload bytes are stable until the in-order release, so
no payload copy happens on the rx side at all.

Non-IPv4 frames bypass classification and are punted to the host
disposition (the STN punt analog for un-parseable traffic, reference
plugins/contiv/pod.go:375-381).

``mode="persistent"`` (docs/LATENCY.md lever #2, reworked by the
ISSUE 7 device-ring tentpole) serves the latency-floor regime through
device-resident descriptor rings (pipeline/persistent.PersistentPump +
io/rings.py DeviceDescRing): the dispatch loop COMPACTS pending frames
into VEC-packet descriptor slots (several small frames share one slot
at sequential offsets — the 20 B/pkt budget end-to-end, where the r6
loop shipped a full VEC descriptor per 4-packet veth frame), the ring
stager ships whole windows of slots with ONE transfer each, a device
``lax.while_loop`` drains the window against its rx cursor, and the
tx descriptors ride back in the window's ONE result fetch — zero
io_callbacks in steady state, vs the r6 loop's two ordered blocking
callbacks per frame. Double-buffered windows overlap window N's
writeback with window N+1's refill, so the device never idles between
windows; the refill stage keeps up to ``max_inflight`` slots queued at
the stager. Shutdown is race-free: the collector only exits once the
dispatcher has signalled done AND the hand-off queue is drained, so a
frame submitted during stop() still reaches the tx writer (ADVICE
r5); frames abandoned mid-flight by stop() are counted as
``drops_shutdown``, tx-ring-full discards as ``drops_tx_stall``,
batches whose device result never came back (loop death, fetch
failure, timeout) as ``drops_error`` (daemon rx overflow is
``drops_rx_full`` on its side) — the
``vpp_tpu_pump_drops_total{reason=}`` attribution the r5 goodput
number lacked. The VPP analog is the eternal worker dispatch loop:
the graph scheduler never re-launches per frame (reference
docs/VPP_PACKET_TRACING_K8S.md:28-50). Trades:

  * frames process one window at a time in submission order — the
    latency-floor regime with window-amortized overhead; peak batch
    throughput still belongs to the dispatch ladder's deep coalesce;
  * side programs serialize behind the ring windows, so the ICMP
    error path stays disabled in this mode, and config swaps RESTART
    the ring (sessions carried over, the window program re-used from
    the process-wide jit cache) — detected per-frame via ``dp.epoch``.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Callable, Optional, Union

import numpy as np

from vpp_tpu.io.rings import VEC, IORingPair
from vpp_tpu.pipeline.dataplane import (
    PACKED_IN_ROWS,
    count_device_transfer,
    pack_packet_columns,
    unpack_packet_input,
)
from vpp_tpu.pipeline.vector import Disposition, PacketVector
from vpp_tpu.testing import faults

log = logging.getLogger("pump")

_SENTINEL = object()

# Drop-cause stats keys — one per attributed loss reason. The
# collector's vpp_tpu_pump_drops_total reason map
# (stats/collector.py PUMP_DROP_REASONS) must stay in lockstep; the
# tools/lint.py --counters pass enforces it (ISSUE 13 satellite), so a
# new drop cause added on either side without its twin fails tier-1.
PUMP_DROP_KEYS = ("drops_rx_full", "drops_tx_stall", "drops_shutdown",
                  "drops_error", "drops_overload",
                  # tenant token-bucket overage dropped ON DEVICE
                  # (DROP_TENANT verdicts, counted off the aux rider —
                  # ISSUE 14); the reason label is "tenant_quota"
                  "drops_tenant_quota")

# governor ticks a quiet priority lane holds its last p99 observation
# for before reading as no-signal (io/pump.py _gov_observe lane
# discipline — the governor then drifts back to the resting shape)
GOV_PRI_STALE_TICKS = 20

# duck-typed stand-in for rings.Frame: push_packed only reads .cols
# (contiguous column block views), .n and .payload
_IcmpFrame = collections.namedtuple("_IcmpFrame",
                                    ("cols", "n", "epoch", "payload"))

# rings.Frame plus its stable ring-order id (rid = frames ever
# released before it + its pending index — stable for a frame's whole
# lifetime). The express priority lane (ISSUE 13) dispatches OUT of
# ring order, but the SPSC rx ring can only release its oldest slot —
# so the writer marks frames done by rid and releases the contiguous
# done-prefix (_release_done), never a slot whose predecessors are
# still in flight.
_RidFrame = collections.namedtuple(
    "_RidFrame", ("cols", "n", "epoch", "payload", "rid"))


class DataplanePump:
    def __init__(self, dataplane, rings: IORingPair,
                 poll_s: float = 0.0002,
                 max_batch: int = 2048,
                 depth: int = 8,
                 workers: Optional[int] = None,
                 lat_window: int = 4096,
                 icmp_src_ip: int = 0,
                 mode: str = "dispatch",
                 max_inflight: Optional[int] = None,
                 fetch_workers: Optional[int] = None,
                 chain_k: int = 0,
                 fetch_delay: Union[None, float, Callable] = None,
                 ring_slots: int = 8,
                 ring_windows: int = 2,
                 ring_fault_limit: int = 3,
                 governor=None,
                 priority=None,
                 tenants=None,
                 tenant_quantum: int = 0):
        """``max_batch``: largest coalesced device batch (packets);
        ``max_inflight``: in-flight batches before the dispatch stage
        backpressures (``depth`` is the legacy alias — ``max_inflight``
        wins when both are given);
        ``fetch_workers``: concurrent result fetchers (legacy alias
        ``workers``) — None auto-picks: on a REMOTE device a fetch is
        an RPC round trip (~100 ms on the axon tunnel) and W workers
        overlap W round trips, so 8; on the CPU backend a fetch is a
        local memcpy and extra blocked threads only churn the GIL
        against the dispatch/writer threads (measured 14% throughput
        loss at 8 workers on a single-core host), so 1.
        ``chain_k``: >= 2 arms the adaptive chainer — backlog past one
        full ``max_batch`` bucket folds into ONE
        ``process_packed_chain`` dispatch of K stacked buckets, K a
        power of two up to ``chain_k`` (rounded down to a power of
        two): the rung ladder bounds the jit cache to log2(chain_k)
        chain shapes while a partial fold never pads more than 2× its
        real depth. 0/1 disables chaining.
        ``fetch_delay``: fault injection for tests/bench — seconds (or
        ``callable(seq) -> seconds``) slept by the fetch worker before
        touching the device result, simulating a slow result transport.
        ``icmp_src_ip``: with a non-zero address (the node's pod gateway
        IP), TTL-expired and no-route drops generate ICMP
        time-exceeded/net-unreachable back to the sender (io/icmp.py;
        VPP's ip4-icmp-error node).
        ``mode``: "dispatch" (default, the pipelined ladder) or
        "persistent" (device-resident descriptor rings — module docs).
        ``ring_slots``/``ring_windows``: persistent-mode device-ring
        geometry (frames per window / staging double-buffers —
        io/rings.py DeviceDescRing; config-static shape like
        ``sess_ways``, knobs ``io.io_ring_slots``/``io.io_ring_windows``
        in cmd/config.py).
        ``ring_fault_limit``: degraded-mode escape hatch (ISSUE 8;
        knob ``io.io_ring_fault_limit``): after this many resident-ring
        deaths over the pump's lifetime, persistent mode FALLS BACK to
        the dispatch ladder instead of relaunching the ring forever —
        a wedged device-ring path (driver fault, transfer errors) then
        degrades to the slower-but-working mode and the
        ``vpp_tpu_degraded{component="ring"}`` gauge says so. 0
        disables the fallback entirely: the ring relaunches forever,
        paced by a jittered backoff (note: the pre-ISSUE-8 code
        relaunched exactly once and let a second death kill the
        dispatch thread — 0 keeps the pump alive instead).
        ``governor``: optional io/governor.py LatencyGovernor (ISSUE
        13) — the closed-loop SLO controller; the pump binds it to its
        geometry, ticks it on the dispatch thread, applies its window
        fill / in-flight / coalesce limits host-side, and sheds bulk
        admission in brownout as attributed ``drops_overload``.
        ``priority``: optional PriorityFilter designating reflex
        flows: they form their own coalesce groups, preempt bulk
        windows in the ring staging path, and are never shed.
        ``tenants``: optional tenancy/sched.py TenantClassifier
        (ISSUE 14) — bulk frames are lane-classified per tenant at
        the scan frontier and dequeued WEIGHTED-FAIR (virtual-time
        WFQ over per-tenant queues), so one tenant's backlog cannot
        starve the rest; in governor brownout the pump sheds from the
        tenant with the most backlog per unit weight (the hog)
        instead of FIFO order, attributed ``drops_overload`` with
        per-tenant accounting. The priority lane still outranks every
        tenant queue (reflexes first), and tenant groups are
        single-tenant so shedding/attribution stay clean (the chain
        folder stays disengaged under tenant scheduling).
        ``tenant_quantum``: cap (packets) on one tenant's WFQ service
        take (0 = a full slot/batch, the throughput shape). A WFQ
        delay bound scales with the service quantum x active lanes,
        so a smaller quantum bounds how long a light tenant's frame
        sits behind another tenant's bulk inside the shared window
        pipeline — at the cost of more window exchanges per delivered
        packet (the same latency/throughput dial as the ring fill;
        ``io.io_tenant_quantum``)."""
        if mode not in ("dispatch", "persistent"):
            raise ValueError(f"unknown pump mode {mode!r}")
        self.mode = mode
        self.dp = dataplane
        self.rings = rings
        self.poll_s = poll_s
        if fetch_workers is not None:
            workers = fetch_workers
        if workers is None:
            import jax

            workers = 1 if jax.default_backend() == "cpu" else 8
        self.max_inflight = int(max_inflight if max_inflight is not None
                                else depth)
        chain_k = int(chain_k)
        # round down to a power of two: the chain rung ladder is
        # K ∈ {2, 4, …, chain_k} and a non-pow2 cap would add a rung
        # no fold ever uses
        self.chain_k = (1 << (chain_k.bit_length() - 1)) \
            if chain_k >= 2 else 0
        self._fetch_delay = fetch_delay
        self.icmp = None
        self._icmp_scratch = None
        if icmp_src_ip and mode == "persistent":
            log.warning("persistent pump mode: ICMP error generation "
                        "disabled (side programs park behind the "
                        "resident loop)")
            icmp_src_ip = 0
        if icmp_src_ip:
            from vpp_tpu.io.icmp import IcmpErrorGen

            self.icmp = IcmpErrorGen(icmp_src_ip, VEC, rings.tx.snap)
            self._icmp_scratch = np.zeros((VEC, rings.tx.snap), np.uint8)
            # built error batches queued to the error-path thread (its
            # device round trips must not block the tx writer); bounded
            # — overflow counts as rate-limit suppression
            self._icmp_q: "queue.Queue" = queue.Queue(maxsize=8)
        # native fast-path scratch (single dispatch / single tx-writer
        # thread each, so plain reuse is safe): per-batch frame base
        # pointers + counts for pio_pack_batch, per-frame drop causes
        # out of pio_unpack_to_slot
        self._pack_bases = np.zeros(rings.rx.ring.n_slots, np.uint64)
        self._pack_ns = np.zeros(rings.rx.ring.n_slots, np.uint32)
        self._cause = np.zeros(VEC, np.int32)
        self._icmp_cause = np.zeros(VEC, np.int32)
        self.max_batch = max(VEC, int(max_batch))
        # geometric bucket ladder VEC, 4·VEC, 16·VEC, … up to max_batch:
        # a partial backlog pads to the next bucket, not straight to
        # max_batch — padding is wasted boundary bytes (a 10-frame
        # backlog padded to 16384 uploads 6× the useful data), and on a
        # transfer-limited transport that waste IS lost throughput.
        # Cost: one extra jit compile per rung (precompile via
        # ``bucket_sizes()``).
        self.buckets = []
        b = VEC
        while b < self.max_batch:
            self.buckets.append(b)
            b *= 4
        self.buckets.append(self.max_batch)
        self.workers = max(1, int(workers))
        self.stats = {
            "frames": 0, "pkts": 0, "batches": 0, "tx_ring_full": 0,
            "max_coalesce": 0, "batch_errors": 0,
            # cumulative seconds per stage (profiling; `show io` /
            # bench read these to attribute wire-path time). t_fetch
            # is the serial result COPY; t_fetch_wait is the wait for
            # the device result to become ready — time overlapped with
            # the other in-flight batches, not a serial path cost.
            "t_pack": 0.0, "t_dispatch": 0.0, "t_fetch": 0.0,
            "t_fetch_wait": 0.0, "t_write": 0.0,
            # overlap occupancy: batches dispatched but not yet written
            # (the ladder's live depth) + high-water mark, and how often
            # the adaptive chainer folded backlog into one K-stack
            "inflight": 0, "inflight_peak": 0,
            "chain_batches": 0, "chain_k_peak": 0,
            # two-tier dispatch (pipeline/graph.py pipeline_step_auto):
            # dispatches fully served by the classify-free fast kernel
            # (a chain fold counts ONCE, and only when every sub-batch
            # went fast — comparable to "batches"), plus the raw
            # session-hit/alive packet accumulators behind the
            # fastpath_hit_pct gauge (hits/alive is the regime signal —
            # WHY batches do or don't dispatch fast)
            "fastpath_batches": 0, "fastpath_hits": 0, "fastpath_alive": 0,
            # session-table pressure riders (aux rows 3/4): inserts that
            # lost the intra-batch way election (retried next packet)
            # and ways reclaimed by eviction (expired + victim, both
            # tables) — the set-associative table's congestion signals,
            # delivered in the SAME fetch as the packed results
            "sess_insert_fails": 0, "sess_evictions": 0,
            # per-packet ML stage riders (aux rows 5..7, ISSUE 10):
            # packets scored / flagged / dropped by the model across
            # every dispatch form (packed, chained, device-ring) — the
            # packed paths never fetch StepStats, so the marking
            # signal rides the same aux fetch as the fastpath rows
            "ml_scored": 0, "ml_flagged": 0, "ml_drops": 0,
            # device-telemetry riders (aux rows 8/9, ISSUE 11):
            # packets whose wire latency the device histogrammed, and
            # packets folded into the heavy-hitter flow sketch — both
            # 0 with dataplane.telemetry off
            "tel_observed": 0, "tel_sketched": 0,
            # drops by CAUSE (packets; ISSUE 7 satellite — the r5
            # goodput number hid WHERE persistent-mode loss happened):
            # tx_stall = tx-ring-full discards by the writer,
            # shutdown = frames abandoned mid-flight by stop(),
            # error = a dispatched batch whose result never came back
            # (loop death, fetch failure, result timeout — counted
            # where the writer releases the frames unwritten),
            # rx_full = rx-ring overflow — counted by the IO daemon
            # (io/daemon.py drops_rx_full; the pump's own key stays 0
            # and exists so the vpp_tpu_pump_drops_total{reason=}
            # family always exports every reason),
            # overload = bulk frames the latency governor refused at
            # admission in brownout (ISSUE 13 — shedding is explicit
            # and attributed, never silent queue growth)
            "drops_tx_stall": 0, "drops_shutdown": 0, "drops_rx_full": 0,
            "drops_error": 0, "drops_overload": 0,
            # tenancy (ISSUE 14): device token-bucket drops + slice
            # insert failures off aux rows 10/11, and tenant
            # classifications the pump.tenant_starve fault seam
            # demoted to the default tenant (chaos testing)
            "drops_tenant_quota": 0, "tenant_sess_quota_fails": 0,
            "tenant_starved": 0,
            # priority lane (ISSUE 13): frames/packets classified into
            # the reflex lane by the PriorityFilter, windows the ring
            # stager shipped early for one (synced from the
            # PersistentPump), and priority marks the
            # "pump.priority_starve" fault seam demoted to bulk
            "priority_frames": 0, "priority_pkts": 0,
            "priority_preempts": 0, "priority_starved": 0,
            # express-vs-bulk service order under tenant lanes
            # (ISSUE 14): WFQ bulk-frame admissions at the most recent
            # express take — diagnostics, not exported
            "priority_admit_bulk_seq": 0,
            # device-ring telemetry (persistent mode; synced from the
            # PersistentPump by the collect loop + at stop-merge):
            # windows exchanged, frames staged, live in-flight windows,
            # dispatched-minus-written-back windows (tx writeback lag),
            # and host callbacks made by the device program — the ring
            # steady state makes NONE (io_callbacks stays 0; bench.py
            # reports io_wire_callbacks_per_window from it)
            "ring_windows": 0, "ring_frames": 0, "ring_inflight": 0,
            "ring_lag": 0, "io_callbacks": 0,
        }
        # dispatch→tx latency of recent batches, seconds (experienced
        # added latency of the device leg; ring-wait not included — the
        # bench measures full ring-to-ring with its own timestamps).
        # _lat_lock guards append vs snapshot: iterating a deque while
        # the tx writer appends raises RuntimeError (reachable from the
        # CLI's `show io` → latency_us()). It also guards the
        # concurrent-writer stats (t_fetch*, inflight*): += is a
        # load/add/store that interleaves across fetch workers.
        self.batch_lat = collections.deque(maxlen=lat_window)
        # the reflex lane's own dispatch→tx latency window (ISSUE 13):
        # the governor steers on THIS distribution when a priority
        # filter is attached — the SLO protects reflex traffic, so
        # bulk batching latency must not drive the control loop into
        # brownout while the lane itself meets the SLO. _pri_total
        # counts appends so the observer can tell fresh samples from
        # a quiet lane.
        self.pri_lat = collections.deque(maxlen=1024)
        self._pri_total = 0
        self._lat_lock = threading.Lock()
        # optional Prometheus Histogram (stats/collector.py set_pump):
        # every batch latency is observed as a real distribution —
        # histogram_quantile() aggregates across nodes where the
        # p50/p99 window gauges cannot
        self.latency_hist = None
        # optional Histogram (vpp_tpu_fastpath_batch_seconds): the
        # dispatch→tx latency of batches the classify-free kernel
        # served — the measured fast-tier distribution next to the
        # all-batches one above
        self.fastpath_hist = None
        self._inflight: "queue.Queue" = queue.Queue(
            maxsize=self.max_inflight)
        # express fast path through the fetch stage (ISSUE 13): the
        # fetch workers drain this queue FIRST, so a priority batch
        # waits for at most the fetch already in progress — never for
        # the whole FIFO of queued bulk fetches
        self._inflight_pri: "queue.Queue" = queue.Queue(
            maxsize=self.max_inflight)
        # live fetch workers (under _lat_lock): the tx writer's
        # shutdown rescue engages only once every fetcher has exited
        self._fetchers_live = 0
        self._done: dict = {}               # seq -> completed batch
        self._done_cv = threading.Condition()
        self._seq = 0
        # guards the rid bookkeeping shared by dispatch (takes) and
        # the tx writer (completions + in-order releases). A release
        # shifts every pending index down, but rids are stable:
        # rid = _consumed_base + pending index.
        #   _taken      rids routed into a group (incl. queued express)
        #   _done_rids  rids completed by the writer, awaiting their
        #               turn in the ring-order release prefix
        #   _express    priority rids awaiting express dispatch (also
        #               in _taken so bulk takes skip them)
        #   _scan_rid   classification frontier: every pending frame
        #               below it has been lane-classified exactly once
        self._held_lock = threading.Lock()
        self._taken: set = set()
        self._done_rids: set = set()
        self._express: "collections.deque" = collections.deque()
        self._consumed_base = 0
        self._scan_rid = 0
        # the tx frame ring is SPSC: its reserve/commit protocol
        # permits ONE producer. The in-order writer and the ICMP
        # error-path thread both push, so their pushes serialize here.
        self._tx_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: list = []
        # persistent mode (module docs): the resident-loop handle, the
        # table epoch it was started against, the FIFO tying each
        # submitted frame to the loop's (ordered) result stream, and
        # the dispatch-done event the collector's exit is gated on
        # (ADVICE r5: an Empty+_stop exit can orphan a frame the
        # dispatcher was still handing off)
        self._ppump = None
        self._persist_epoch = -1
        self._persist_q: "queue.Queue" = queue.Queue(
            maxsize=self.max_inflight)
        self._persist_dispatch_done = threading.Event()
        # device-ring geometry (persistent mode) + the accumulator the
        # live PersistentPump counters fold into across epoch restarts
        self.ring_slots = int(ring_slots)
        self.ring_windows = int(ring_windows)
        self._ring_accum = {"ring_windows": 0, "ring_frames": 0,
                            "io_callbacks": 0, "priority_preempts": 0}
        # reflex-plane latency governor + priority lane (ISSUE 13;
        # io/governor.py). The governor is HOST-SIDE ONLY: it shapes
        # window fill / in-flight depth / coalesce caps and admission
        # — all values the device programs already take dynamically —
        # so a governed pump traces ZERO new step variants
        # (jit-budget-proved in tests/test_governor.py). Ticked on the
        # dispatch thread; a crashed governor wedges itself and the
        # pump keeps the last-known window shape.
        self.governor = governor
        self.priority = priority
        # tenancy lanes (ISSUE 14; vpp_tpu/tenancy/sched.py): the
        # classifier routes bulk frames into per-tenant WFQ queues at
        # the scan frontier; per-tenant host counters live under
        # _lat_lock, the scheduler itself under _held_lock (it extends
        # the rid bookkeeping).
        self.tenants = tenants
        self._tnt_sched = None
        if tenants is not None:
            from vpp_tpu.tenancy.sched import TenantScheduler

            self._tnt_sched = TenantScheduler(tenants.weights)
        self.tenant_quantum = int(tenant_quantum) if tenant_quantum \
            else 0
        self.tenant_io: dict = {}
        self._tnt_admit_frames = 0  # global WFQ admission seq (_lat_lock)
        if governor is not None:
            slots = (self.ring_slots if mode == "persistent"
                     else max(1, self.max_batch // VEC))
            # with a priority lane attached the governor runs in
            # EXPRESS mode: brownout keys off the physical rx queue
            # bound, not the reflex envelope (io/governor.py bind doc)
            governor.bind(slots, self.max_inflight,
                          queue_cap=(rings.rx.ring.n_slots // 2
                                     if priority is not None else None))
        # governor observation state (dispatch-thread only): last
        # device-histogram bins (delta quantiles per tick) and the
        # ring's last cumulative fill snapshot (recent avg occupancy)
        self._gov_bins = None
        self._gov_fill_last = (0, 0)
        self._gov_pri_seen = 0
        # last reflex-lane p99 + how many ticks it has been stale: a
        # quiet lane holds its observation this many ticks, then
        # reads as no-signal (never bulk fallback — lane discipline)
        self._gov_pri_p99: Optional[float] = None
        self._gov_pri_stale = 0
        # ring→dispatch degraded fallback (ISSUE 8): resident-ring
        # deaths counted over the pump lifetime (dispatch-thread-only,
        # so unlocked); degraded_ring is the one-way flag the
        # collector/CLI read (a plain bool flip — torn reads are
        # impossible and the writer is the single dispatch thread)
        self.ring_fault_limit = int(ring_fault_limit)
        self._ring_faults = 0
        self.degraded_ring = False
        # pacing between ring relaunches (dispatch-thread-only): a
        # ring dying instantly on every relaunch must not hot-spin
        # fault→relaunch→fault — especially with ring_fault_limit=0
        # (retry forever)
        from vpp_tpu.net.backoff import Backoff

        self._ring_backoff = Backoff(base=0.1, cap=5.0)

    def bucket_sizes(self) -> list:
        """The dispatch bucket ladder — precompile ``process_packed``
        at each of these batch sizes before offering traffic."""
        return list(self.buckets)

    def warm(self) -> list:
        """Compile every dispatch bucket rung (blocking), plus the one
        chain shape when the adaptive chainer is armed. Call before
        ``start()``/before offering traffic: a rung's first jit compile
        costs 20-40 s on TPU, and paying it lazily inside the dispatch
        thread stalls the rx rings and drops live traffic.

        Persistent mode: launches the device-ring pump (the window
        program's one process-wide compile) and round-trips an
        all-invalid frame through a 1-slot window, so the program is
        compiled and hot before traffic is offered."""
        import jax

        from vpp_tpu.pipeline.dataplane import packed_input_zeros

        if self.mode == "persistent":
            self._persist_start()
            self._ppump.submit(packed_input_zeros(VEC),
                               now=self.dp.clock_ticks())
            self._ppump.result(timeout=300.0)
            return [VEC]
        for bucket in self.buckets:
            jax.block_until_ready(
                self.dp.process_packed(packed_input_zeros(bucket))
            )
        k = 2
        while k <= self.chain_k:
            jax.block_until_ready(self.dp.process_packed_chain(
                np.zeros((k, PACKED_IN_ROWS, self.max_batch), np.int32)
            ))
            k *= 2
        return list(self.buckets)

    # --- lifecycle ---
    def start(self) -> "DataplanePump":
        if self.mode == "persistent":
            names = [(self._persist_dispatch_loop, "dp-pump-dispatch"),
                     (self._persist_collect_loop, "dp-pump-collect"),
                     (self._write_loop, "dp-pump-tx")]
        else:
            names = [(self._dispatch_loop, "dp-pump-dispatch"),
                     (self._write_loop, "dp-pump-tx")]
            names += [(self._fetch_loop, f"dp-pump-fetch{i}")
                      for i in range(self.workers)]
            if self.icmp is not None:
                names.append((self._icmp_loop, "dp-pump-icmp"))
        for fn, name in names:
            t = threading.Thread(target=fn, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def stop(self, join_timeout: Optional[float] = None) -> bool:
        """Stop the pump; returns True when every thread has exited.

        Default join is unbounded: the caller tears the rings down right
        after, and a thread still inside dp.process (a first-batch jit
        compile easily exceeds seconds) must not race ring memory being
        freed — that's a use-after-free into shared memory."""
        self._stop.set()
        try:
            self._inflight.put_nowait(_SENTINEL)
        except queue.Full:
            pass  # fetchers are draining; they check _stop per item
        with self._done_cv:
            self._done_cv.notify_all()
        ok = True
        for t in self._threads:
            t.join(timeout=join_timeout)
            ok = ok and not t.is_alive()
        return ok

    # --- overlap occupancy accounting (dispatch + writer + collector) --
    def _inflight_inc(self) -> None:
        with self._lat_lock:
            d = self.stats["inflight"] + 1
            self.stats["inflight"] = d
            if d > self.stats["inflight_peak"]:
                self.stats["inflight_peak"] = d

    def _inflight_dec(self) -> None:
        with self._lat_lock:
            self.stats["inflight"] -= 1

    # --- dispatch: rx ring -> device (async) ---
    def _frame_priority(self, f) -> bool:
        """Classify one rx frame into the reflex lane (ISSUE 13;
        io/governor.py PriorityFilter). The "pump.priority_starve"
        fault seam demotes a matched frame to bulk — the chaos suite
        proves starved priority traffic is still CONSERVED (delivered
        or attributed), just unprioritized."""
        if self.priority is None:
            return False
        if not self.priority.frame_match(f):
            return False
        try:
            faults.fire("pump.priority_starve")
        except faults.FaultInjected:
            # dispatch-thread-only counter (like stats["batches"]);
            # re-peeked frames may re-classify, so this counts starve
            # EVENTS, not distinct frames
            self.stats["priority_starved"] += 1
            return False
        return True

    def _frame_tenant(self, f) -> int:
        """Classify one bulk frame into its tenant lane (ISSUE 14).
        The "pump.tenant_starve" fault seam demotes a frame to the
        DEFAULT tenant — it loses its weighted lane (schedulable and
        sheddable as tenant 0) but is still CONSERVED, which the chaos
        schedule proves."""
        try:
            faults.fire("pump.tenant_starve")
        except faults.FaultInjected:
            # dispatch-thread-only counter (like priority_starved)
            self.stats["tenant_starved"] += 1
            return 0
        return self.tenants.frame_tenant(f)

    def _scan_express(self, rx, hold_cap: int) -> None:
        """Advance the lane-classification frontier over newly arrived
        frames: priority ones to the express queue (ISSUE 13), and —
        with a TenantClassifier attached (ISSUE 14) — every other
        frame into its tenant's WFQ queue. Each frame is classified
        exactly ONCE (the frontier is monotone in rid); lane-routed
        rids are marked taken immediately so bulk takes skip them.
        The frontier STALLS (resumes next round) while the lanes hold
        ``hold_cap`` rids, so a burst backpressures the producer
        instead of marking every ring slot taken at once.
        Classification runs OUTSIDE _held_lock — the frame cannot be
        released before it is taken and completed, so its views are
        stable, and the tx writer's release path must not wait out
        numpy matching. No-op without a priority filter or tenant
        classifier."""
        if self.priority is None and self.tenants is None:
            return
        while True:
            with self._held_lock:
                # the taken+done bound matters only for PURE tenant
                # lanes, where the scan marks EVERY frame taken as it
                # routes it: without it a burst would claim the whole
                # ring at once. It must NOT gate any config with a
                # priority filter — the express lane's contract is to
                # classify and jump the bulk queue precisely while
                # bulk holds the ring at its cap (ISSUE 13), and the
                # frontier is monotone, so stalling it on bulk
                # occupancy would make reflex CLASSIFICATION
                # bulk-service-bound. With both lanes attached the
                # WFQ queues stay bounded by the rx ring itself.
                if (len(self._express) >= hold_cap
                        or (self.tenants is not None
                            and self.priority is None
                            and len(self._taken) + len(self._done_rids)
                            >= hold_cap)):
                    return
                base = self._consumed_base
                rid = max(self._scan_rid, base)
                if rid >= base + rx.pending():
                    return
                f = rx.peek_nth(rid - base)
                if f is None:
                    return
                self._scan_rid = rid + 1
            if self.priority is not None and self._frame_priority(f):
                with self._held_lock:
                    self._taken.add(rid)
                    self._express.append(rid)
                self.stats["priority_frames"] += 1
                self.stats["priority_pkts"] += f.n
                continue
            if self.tenants is not None:
                tid = self._frame_tenant(f)
                with self._held_lock:
                    self._taken.add(rid)
                    self._tnt_sched.push(tid, rid, f.n)
                with self._lat_lock:
                    io = self.tenant_io.setdefault(
                        tid, {"frames": 0, "pkts": 0, "shed_pkts": 0,
                              "admitted_pkts": 0})
                    io["frames"] += 1
                    io["pkts"] += f.n

    def _take_express(self, rx):
        """Pop the oldest express rid into a one-frame group, or None.
        The express lane is what actually bounds reflex queueing: a
        priority frame deep behind a bulk backlog is dispatched NOW,
        out of ring order, while its rx slot is released later in
        ring order by the writer's done-prefix. Never refuses a
        queued rid: express rids are already held, so popping frees
        ring slots (dispatch → complete → release) — refusing under
        pressure would wedge exactly the all-priority burst the lane
        exists for."""
        with self._held_lock:
            if not self._express:
                return None
            rid = self._express.popleft()
            f = rx.peek_nth(rid - self._consumed_base)
            if f is None:  # unreachable: taken rids stay pending
                self._taken.discard(rid)
                return None
        if self._tnt_sched is not None:
            with self._lat_lock:
                # express-vs-bulk service ORDER signal (the tenant
                # last_admit_seq analog): how many bulk frames the WFQ
                # lanes had admitted when this reflex frame took
                # service — bounded regardless of bulk backlog depth
                # is the ISSUE 13 contract, now observable poll-free
                self.stats["priority_admit_bulk_seq"] = \
                    self._tnt_admit_frames
        return [_RidFrame(f.cols, f.n, f.epoch, f.payload, rid)]

    def _take_tenant_group(self, rx, max_pkts: Optional[int] = None):
        """Weighted-fair bulk take (ISSUE 14): serve the tenant with
        the least virtual time one single-tenant coalesce group (its
        queued frames in arrival order, up to ``max_pkts`` packets).
        Returns ``(tid, [group])`` or None. Single-tenant groups keep
        shedding and accounting attributable — the chain folder stays
        disengaged under tenant scheduling. ``tenant_quantum`` caps
        the take (the WFQ delay-bound dial — ctor doc)."""
        if max_pkts is None:
            max_pkts = self.max_batch
        if self.tenant_quantum:
            max_pkts = min(max_pkts, self.tenant_quantum)
        with self._held_lock:
            tid = self._tnt_sched.pick()
            if tid is None:
                return None
            group = self._pop_tenant_group_locked(rx, tid, max_pkts)
        if not group:
            return None
        with self._lat_lock:
            io = self.tenant_io.setdefault(
                tid, {"frames": 0, "pkts": 0, "shed_pkts": 0,
                      "admitted_pkts": 0})
            io["admitted_pkts"] += sum(f.n for f in group)
            # monotone frame-admission sequence across ALL tenants,
            # stamped per tenant at its most recent WFQ take: a
            # poll-free service-ORDER signal (tenant A's last_admit_seq
            # minus its own admitted frames = frames other tenants got
            # before A finished — how the fairness test proves WFQ vs
            # FIFO without racing a snapshot against the drain).
            # Untakes (ring-fault requeue) do not rewind it: it orders
            # admissions, it does not conserve them.
            self._tnt_admit_frames += len(group)
            io["last_admit_seq"] = self._tnt_admit_frames
        return tid, [group]

    def _pop_tenant_group_locked(self, rx, tid: int,
                                 max_pkts: int) -> list:
        """Dequeue up to ``max_pkts`` packets of ``tid`` from its WFQ
        queue into a ``_RidFrame`` group (the shared body of the take
        and shed paths — caller holds ``_held_lock``)."""
        frames = self._tnt_sched.pop(tid, max_pkts)
        base = self._consumed_base
        group = []
        for rid, _n in frames:
            f = rx.peek_nth(rid - base)
            if f is None:  # unreachable: taken rids stay pending
                self._taken.discard(rid)
                continue
            group.append(_RidFrame(f.cols, f.n, f.epoch, f.payload,
                                   rid))
        return group

    def _untake_tenant(self, tid: int, frames: list) -> None:
        """Return un-dispatched tenant frames to the HEAD of their WFQ
        queue (the ring-fault fallback path): the scan frontier is
        monotone, so a plain untake would orphan them below it."""
        with self._held_lock:
            self._tnt_sched.requeue_front(
                tid, [(f.rid, f.n) for f in frames])
        with self._lat_lock:
            io = self.tenant_io.get(tid)
            if io is not None:
                io["admitted_pkts"] -= sum(f.n for f in frames)

    def _shed_tenant(self, rx) -> bool:
        """Brownout shedding under tenant lanes (ISSUE 14): refuse one
        group from the tenant with the MOST backlog per unit weight —
        per-tenant-weighted shedding, never FIFO — attributed
        ``drops_overload`` plus the per-tenant ledger. Returns False
        with nothing queued (the caller falls through to take/idle)."""
        with self._held_lock:
            tid = self._tnt_sched.shed_pick()
            if tid is None:
                return False
            group = self._pop_tenant_group_locked(rx, tid, self.max_batch)
        if not group:
            return False
        with self._lat_lock:
            io = self.tenant_io.setdefault(
                tid, {"frames": 0, "pkts": 0, "shed_pkts": 0,
                      "admitted_pkts": 0})
            io["shed_pkts"] += sum(f.n for f in group)
        self._post_batchless([group], "drops_overload")
        return True

    def tenant_io_snapshot(self) -> dict:
        """Per-tenant IO-side counters + live queue state + weights
        (host scalars; the collector/CLI read)."""
        with self._lat_lock:
            io = {t: dict(v) for t, v in self.tenant_io.items()}
        queued = {}
        if self._tnt_sched is not None:
            with self._held_lock:
                queued = self._tnt_sched.snapshot()
        weights = dict(self.tenants.weights) if self.tenants else {}
        names = dict(self.tenants.names) if self.tenants else {}
        return {"io": io, "queued": queued, "weights": weights,
                "names": names}

    def _take_groups(self, rx, hold_cap: int, chain_cap: int,
                     max_pkts: Optional[int] = None) -> list:
        """Peek pending BULK frames (in ring order, skipping rids the
        express lane took) into coalesce groups by PACKET count: a
        group closes when the next frame would overflow ``max_pkts``
        packets (default ``max_batch``; persistent mode compacts at
        the VEC descriptor-slot width). One group = one packed batch;
        2+ groups = the chainer has a K-stack to fold. With a
        priority filter attached, only frames below the
        classification frontier are takeable (scan runs first each
        loop). Holds _held_lock across the whole peek block (a
        concurrent writer release shifts pending indices)."""
        if max_pkts is None:
            max_pkts = self.max_batch
        with self._held_lock:
            base = self._consumed_base
            pending = rx.pending()
            end_rid = (min(self._scan_rid, base + pending)
                       if self.priority is not None else base + pending)
            budget = hold_cap - len(self._taken) - len(self._done_rids)
            groups, cur, cur_n = [], [], 0
            rid = base
            while rid < end_rid and budget > 0 \
                    and len(groups) < chain_cap:
                if rid in self._taken or rid in self._done_rids:
                    rid += 1
                    continue
                f = rx.peek_nth(rid - base)
                if f is None:
                    break
                if cur and cur_n + f.n > max_pkts:
                    groups.append(cur)
                    cur, cur_n = [], 0
                    continue
                cur.append(_RidFrame(f.cols, f.n, f.epoch, f.payload,
                                     rid))
                cur_n += f.n
                budget -= 1
                rid += 1
            if cur and len(groups) < chain_cap:
                groups.append(cur)
            if len(groups) > 1:
                # trim to the largest chain rung ≤ the fold (a power
                # of two — the precompiled ladder); untrimmed groups
                # stay pending for the next dispatch
                groups = groups[:1 << (len(groups).bit_length() - 1)]
            for g in groups:
                for f in g:
                    self._taken.add(f.rid)
        return groups

    def _untake_any(self, frames: list, priority: bool,
                    tenant) -> None:
        """Route an un-dispatch to the right lane's untake: express
        rids back to the express head, tenant rids back to their WFQ
        queue head (a plain untake would orphan them below the
        monotone scan frontier), plain bulk rids simply untaken."""
        if tenant is not None:
            self._untake_tenant(tenant, frames)
        else:
            self._untake(frames, priority)

    def _untake(self, frames: list, priority: bool = False) -> None:
        """Return un-dispatched frames to the takeable pool (the
        ring-fault fallback path): bulk rids simply become untaken
        (the front scan re-takes them in order); express rids go back
        to the HEAD of the express queue, still marked taken."""
        with self._held_lock:
            if priority:
                self._express.extendleft(f.rid for f in reversed(frames))
            else:
                for f in frames:
                    self._taken.discard(f.rid)

    def _release_done(self, groups: list) -> None:
        """Writer-side completion: mark every frame done by rid, then
        release the CONTIGUOUS done-prefix to the rx ring — the SPSC
        ring only frees its oldest slot, and the express lane may
        complete rids out of order, so a done frame waits for its
        predecessors (its slot views stay valid exactly because the
        release is deferred)."""
        with self._held_lock:
            for g in groups:
                for f in g:
                    self._done_rids.add(f.rid)
                    self._taken.discard(f.rid)
            while self._consumed_base in self._done_rids:
                self._done_rids.discard(self._consumed_base)
                self.rings.rx.release()
                self._consumed_base += 1

    def _backlog(self) -> int:
        """Frames pending in the rx ring that no lane has DISPATCHED
        yet — the governor's queue-depth observation. Tenant-queued
        frames are marked taken at the scan frontier but still wait
        for service, so they count back in."""
        with self._held_lock:
            queued = (self._tnt_sched.total_frames
                      if self._tnt_sched is not None else 0)
            return (self.rings.rx.pending() - len(self._taken)
                    - len(self._done_rids) + queued)

    def _post_batchless(self, groups: list, drop_key: str) -> None:
        """Hand frames to the writer as a BATCHLESS done-item (no tx
        write — the slots still complete and release in ring order)
        with the loss attributed to ``drop_key`` at the decision
        site. The ONE place the 6-field loss-path done-item is built:
        the writer unpacks all six fields and the express jump
        indexes the pri flag, so the tuple shape is load-bearing."""
        with self._lat_lock:
            self.stats[drop_key] += sum(f.n for g in groups for f in g)
        self._inflight_inc()
        with self._done_cv:
            self._done[self._seq] = (None, groups, None,
                                     time.perf_counter(), False, False)
            self._seq += 1
            self._done_cv.notify_all()

    def _shed_group(self, groups: list) -> None:
        """Overload shedding (ISSUE 13): refuse a bulk coalesce group
        at admission while the governor is in brownout — explicit,
        attributed shedding, never silent queue growth."""
        self._post_batchless(groups, "drops_overload")

    # --- latency governor (ISSUE 13; dispatch-thread only) ---
    def _governor_tick(self) -> None:
        """Run one governor control tick when due and push the window
        fill limit to the live ring. The governor itself never raises
        (it wedges one-way after repeated failures — module doc of
        io/governor.py); everything here is host-side shaping, so no
        step variant is ever retraced."""
        gov = self.governor
        if gov is None or not gov.tick_due():
            return
        p99, backlog, delivered, fill_avg = self._gov_observe()
        gov.maybe_tick(p99, backlog, delivered, fill_avg=fill_avg)
        pp = self._ppump
        if pp is not None:
            pp.set_fill_limit(gov.fill)

    def _gov_observe(self) -> tuple:
        """Observation vector for one governor tick: p99 latency (µs)
        — the REFLEX lane's own host window when a priority filter is
        attached and the lane has fresh samples (the SLO protects
        reflex traffic; bulk batching latency must not drive the
        loop), else the device wire-latency histogram's per-tick
        DELTA quantile in persistent mode with telemetry on (the ring
        rider, host scalars only — ISSUE 11's substrate, no device
        transfer at tick time), else the host batch-latency window —
        plus the un-taken rx backlog (frames), delivered-frame count
        (the service-rate estimator's input) and the ring's recent
        average window fill (the lone-window guard)."""
        p99 = None
        pp = self._ppump
        if self.priority is not None:
            # lane discipline: with a priority filter attached the
            # governor NEVER steers on bulk latency — a quiet lane
            # holds its last observation for a bounded staleness
            # window, then reads as no-signal (the governor drifts
            # back to the resting shape; express-mode brownout still
            # keys off queue pressure). Falling back to the
            # bulk-dominated histogram here would pin the ladder at
            # the floor under pure bulk load with nothing to protect.
            with self._lat_lock:
                total = self._pri_total
                snap = (list(self.pri_lat)
                        if total > self._gov_pri_seen else None)
            if snap:
                self._gov_pri_seen = total
                p99 = float(np.percentile(
                    np.asarray(snap) * 1e6, 99))
                self._gov_pri_p99 = p99
                self._gov_pri_stale = 0
            else:
                self._gov_pri_stale += 1
                if self._gov_pri_stale <= GOV_PRI_STALE_TICKS:
                    p99 = self._gov_pri_p99
        elif (pp is not None
                and getattr(self.dp, "_tel_mode", "off") != "off"):
            try:
                tel = self.tel_snapshot()
            except Exception:  # noqa: BLE001 — observation must never
                # kill the dispatch thread; the host window serves
                tel = None
            if tel is not None:
                from vpp_tpu.ops.telemetry import quantiles_from_bins

                bins = np.asarray(tel["bins"], np.int64)
                prev = self._gov_bins
                delta = (bins - prev if prev is not None
                         and prev.shape == bins.shape else bins)
                self._gov_bins = bins
                if int(delta.sum()) > 0:
                    _p50, p99v, _p999 = quantiles_from_bins(delta)
                    p99 = float(p99v)
        if p99 is None and self.priority is None:
            lat = self.latency_us()
            if lat["n"]:
                p99 = float(lat["p99"])
        backlog = self._backlog()
        delivered = int(self.stats["frames"])
        fill_avg = None
        if pp is not None:
            try:
                self._gov_fill_last, fill_avg = pp.fill_avg(
                    self._gov_fill_last)
            except Exception:  # noqa: BLE001 — a dying ring's stats
                # are not worth a dispatch-thread crash
                fill_avg = None
        return p99, backlog, delivered, fill_avg

    def _dispatch_loop(self) -> None:
        rx = self.rings.rx
        # never hold every slot: the producer needs headroom to keep
        # writing while K batches are in flight
        hold_cap = max(2, rx.ring.n_slots - 4)
        while not self._stop.is_set():
            self._governor_tick()
            tracer = self.dp.tracer
            slow = tracer is not None and getattr(tracer, "_armed", 0) > 0
            # the chainer only engages past one full bucket of backlog
            # (depth alone can't absorb it); tracing runs unchained so
            # the tracer sees one full StepResult per dispatch
            chain_cap = 1 if (slow or not self.chain_k) else self.chain_k
            max_pkts = None
            gov = self.governor
            g_infl = self.max_inflight
            if gov is not None:
                # governed coalesce cap: window fill f maps to f·VEC
                # packets per batch — the dispatch-mode analog of the
                # ring's window fill limit. While shedding, groups are
                # taken one at a time so admission decides per group.
                g_fill, g_infl, shedding = gov.limits()
                max_pkts = max(VEC, min(self.max_batch, g_fill * VEC))
                if shedding:
                    chain_cap = 1
            # express lane first (ISSUE 13): a priority frame jumps
            # the whole bulk queue — dispatched NOW in its own group,
            # released later in ring order by the done-prefix
            self._scan_express(rx, hold_cap)
            eg = self._take_express(rx)
            if eg is not None:
                self._dispatch_or_fail([eg], slow, pri=True)
                continue
            if self._inflight.full():
                # don't take a bulk group whose hand-off would BLOCK
                # this thread — a blocked put can't scan for express
                # arrivals, and the lane's bound is the scan cadence
                time.sleep(self.poll_s)
                continue
            if self.tenants is not None:
                # tenant lanes (ISSUE 14): brownout sheds from the
                # hog (backlog/weight max) BEFORE taking, so the
                # weighted-fair take below only ever serves admitted
                # load; the take itself is WFQ — least virtual time
                if gov is not None:
                    if not gov.admit(False, self._backlog()):
                        if self._shed_tenant(rx):
                            continue
                    if self.stats["inflight"] >= g_infl:
                        time.sleep(self.poll_s)
                        continue
                taken = self._take_tenant_group(rx, max_pkts)
                if taken is None:
                    time.sleep(self.poll_s)
                    continue
                self._dispatch_or_fail(taken[1], slow)
                continue
            groups = self._take_groups(rx, hold_cap, chain_cap,
                                       max_pkts)
            if not groups:
                time.sleep(self.poll_s)
                continue
            if gov is not None:
                if not gov.admit(False, self._backlog()):
                    # shedding forces chain_cap=1, so refusal covers
                    # the whole take (exactly one group); the shed
                    # state only flips on THIS thread's ticks, so it
                    # cannot change between limits() and here
                    self._shed_group(groups)
                    continue
                if self.stats["inflight"] >= g_infl:
                    # governed in-flight depth (tighter than the
                    # construction-time queue bound): UNTAKE and
                    # retry instead of sleeping with frames held — a
                    # blocked wait here couldn't scan for express
                    # arrivals, exactly like the full-queue gate above
                    self._untake([f for g in groups for f in g])
                    time.sleep(self.poll_s)
                    continue
            self._dispatch_or_fail(groups, slow)

    def _dispatch_or_fail(self, groups: list, slow: bool,
                          pri: bool = False) -> None:
        """Dispatch with the failed-batch contract: on any dispatch
        error the frames go to the writer as a batchless item so rx
        slots still complete (and release in ring order), with the
        loss attributed to drops_error."""
        try:
            self._dispatch(groups, slow, pri=pri)
        except Exception:
            log.exception("pump dispatch failed (%d frames)",
                          sum(len(g) for g in groups))
            self._post_batchless(groups, "drops_error")

    def _pack_group(self, frames: list, flat: np.ndarray,
                    non_ip: np.ndarray) -> None:
        """ONE native call packs every frame's ring slot into a [5, B]
        int32 bit-packed block (dataplane.pack_packet_columns layout,
        20 B/packet) — the pack/mask loop releases the GIL so the
        daemon's rx thread keeps draining its sockets (VERDICT r3 Next
        #5). Bad (non-IPv4/truncated) slots are masked invalid for the
        pipeline; non-IP is punted after the step via ``non_ip``."""
        from vpp_tpu.native.pktio import pack_batch

        for j, f in enumerate(frames):
            self._pack_bases[j] = f.cols["src_ip"].ctypes.data
            self._pack_ns[j] = f.n
        pack_batch(self._pack_bases, self._pack_ns, len(frames), flat,
                   non_ip)

    def _dispatch(self, groups: list, slow: bool = False,
                  pri: bool = False) -> None:
        K = len(groups)
        tp0 = time.perf_counter()
        # rx-enqueue stamp for the device wire-latency histogram
        # (ISSUE 11): pack start ≈ the frames' peek time in dispatch
        # mode, so the histogram covers pack + the dispatch queue
        stamp_us = 0
        if getattr(self.dp, "_tel_mode", "off") != "off":
            from vpp_tpu.ops.telemetry import tel_clock_us

            stamp_us = tel_clock_us()
        if K == 1:
            total = sum(f.n for f in groups[0])
            # pad to the smallest ladder bucket that fits (a compile
            # costs 20-40 s on TPU, so the ladder is geometric, not
            # per-size): a single frame dispatches at VEC for latency;
            # larger backlogs climb the rungs
            bucket = next(b for b in self.buckets if b >= total)
            flat = np.zeros((PACKED_IN_ROWS, bucket), np.int32)
            non_ip = np.zeros(bucket, np.uint8)
            self._pack_group(groups[0], flat, non_ip)
        else:
            # chain fold: K stacked max_batch buckets, ONE device
            # program. K is a power of two from the precompiled rung
            # ladder (``_take_groups`` trimmed to it), so the jit
            # cache stays at log2(chain_k) chain shapes.
            flat = np.zeros((K, PACKED_IN_ROWS,
                             self.max_batch), np.int32)
            non_ip = np.zeros((K, self.max_batch), np.uint8)
            for k, g in enumerate(groups):
                self._pack_group(g, flat[k], non_ip[k])
        non_ip = non_ip.view(bool)
        self.stats["t_pack"] += time.perf_counter() - tp0
        t0 = time.perf_counter()
        if slow:
            # tracing: run the unpacked step so the tracer captures a
            # full StepResult (multi-transfer — fine while debugging)
            payload = self.dp.process(
                PacketVector(**unpack_packet_input(flat))
            )
        elif K == 1:
            # async dispatch; (out, aux) with the fast-path summary
            # riding the same program (measured on both tiers)
            payload = self.dp.process_packed(flat, with_aux=True,
                                             stamp_us=stamp_us)
        else:
            # async, ([K,5,B], [K,PACKED_AUX_ROWS])
            payload = self.dp.process_packed_chain(
                flat, with_aux=True,
                stamps_us=np.full(K, stamp_us, np.int32))
            self.stats["chain_batches"] += 1
            self.stats["chain_k_peak"] = max(self.stats["chain_k_peak"],
                                             K)
        self.stats["t_dispatch"] += time.perf_counter() - t0
        # unlocked: the dispatch thread is _seq's only writer, so its
        # own read needs no lock; increments publish under _done_cv
        item = (self._seq, payload, groups, non_ip, t0, slow, pri)
        # count the batch in flight BEFORE the hand-off: a fetch worker
        # can complete it (and the writer decrement it) the instant the
        # put lands, so inc-after-put would transiently read -1
        self._inflight_inc()
        target_q = self._inflight_pri if pri else self._inflight
        while True:
            # bounded put that stays responsive to stop(): the fetchers
            # may already have exited, and a blocking put would deadlock
            # the join
            try:
                target_q.put(item, timeout=0.05)
                break
            except queue.Full:
                if self._stop.is_set():
                    self._inflight_dec()
                    with self._lat_lock:
                        self.stats["drops_shutdown"] += sum(
                            f.n for g in groups for f in g)
                    return
        # under _done_cv like the failed-batch path: the tx writer's
        # shutdown gate compares next_seq against _seq under the cv, so
        # an unlocked increment could be observed stale there
        with self._done_cv:
            self._seq += 1
        self.stats["batches"] += 1
        self.stats["max_coalesce"] = max(self.stats["max_coalesce"],
                                         sum(len(g) for g in groups))

    # --- persistent mode: resident device loop (module docs) ---
    def _persist_start(self) -> None:
        from vpp_tpu.pipeline.persistent import PersistentPump

        with self.dp._lock:
            tables = self.dp.tables
            epoch = self.dp.epoch
            fastpath = self.dp._use_fastpath
            classifier = getattr(self.dp, "_classifier_impl", "dense")
            skip_local = getattr(self.dp, "_skip_local", False)
            sweep_stride = getattr(self.dp, "_sweep_stride", None)
            ml_mode = getattr(self.dp, "_ml_mode", "off")
            ml_kind = getattr(self.dp, "_ml_kind", "mlp")
            tel_mode = getattr(self.dp, "_tel_mode", "off")
            tnt_mode = getattr(self.dp, "_tnt_mode", "off")
            sess_hash = getattr(self.dp, "_sess_hash", "fwd")
        self._ppump = PersistentPump(tables, batch=VEC,
                                     fastpath=fastpath,
                                     classifier=classifier,
                                     skip_local=skip_local,
                                     sweep_stride=sweep_stride,
                                     ring_slots=self.ring_slots,
                                     ring_windows=self.ring_windows,
                                     ml_mode=ml_mode,
                                     ml_kind=ml_kind,
                                     tel_mode=tel_mode,
                                     tnt_mode=tnt_mode,
                                     sess_hash=sess_hash,
                                     ).start()
        if self.governor is not None:
            # a relaunched/restarted ring must resume at the
            # governor's CURRENT window shape, not the full-fill
            # default (the wedged-governor freeze contract included)
            self._ppump.set_fill_limit(self.governor.fill)
        self._persist_epoch = epoch

    def _persist_stop_merge(self) -> None:
        """Exit the resident loop and graft its final session state
        back into the dataplane's live tables — the loop threads
        sessions through its carry, so by stop time they are NEWER
        than whatever dp.tables holds (the per-dispatch path commits
        per batch; this is the same continuity, paid at loop exit)."""
        from vpp_tpu.pipeline.tables import (
            FIB_STATE_FIELDS,
            SESSION_FIELDS,
            TELEMETRY_FIELDS,
            TENANCY_STATE_FIELDS,
        )

        if self._ppump is None:
            return
        pp = self._ppump
        try:
            final = pp.stop()
        finally:
            # fold the retiring ring's counters into the accumulator
            # EVEN when stop() raises (a dead ring's exchanges still
            # happened), so stats survive epoch restarts and failures
            # without the exported totals jumping backwards
            self._ring_fold(pp)
            self._ppump = None
            self._ring_stats_sync()
        if final is None:
            return
        # session state, the telemetry planes (ISSUE 11), the tenancy
        # state (ISSUE 14) AND the ECMP accounting plane (ISSUE 15)
        # graft back: all rode the ring's private carry, so by stop
        # time they are newer than whatever dp.tables holds
        sess = {f: getattr(final, f)
                for f in (*SESSION_FIELDS, *TELEMETRY_FIELDS,
                          *TENANCY_STATE_FIELDS, *FIB_STATE_FIELDS)}
        with self.dp._lock:
            if self.dp.tables is not None:
                # DataplaneTables is a NamedTuple pytree, not a dataclass
                self.dp.tables = self.dp.tables._replace(**sess)

    def _persist_restart(self) -> None:
        """Config epoch moved (dp.swap): the resident loop still holds
        the OLD tables. Drain it (ordered results keep flowing to the
        collector), merge sessions, relaunch against the new epoch —
        the persistent-mode equivalent of the per-dispatch path simply
        reading dp.tables on its next batch."""
        log.info("persistent loop restart: table epoch %d -> %d",
                 self._persist_epoch, self.dp.epoch)
        self._persist_stop_merge()
        self._persist_start()

    def _persist_submit_group(self, frames: list,
                              priority: bool = False,
                              tenant=None) -> str:
        """Pack + submit ONE compacted coalesce group (several small
        frames at sequential offsets of a single VEC descriptor slot —
        the header-compaction half of the 20 B/pkt budget) to the ring
        pump and hand its FIFO ticket to the collector. ``priority``
        marks a reflex-lane group: the ring stager ships its window
        immediately instead of draining backlog into it (ISSUE 13).
        Returns "ok",
        "stop" when stop() interrupted the hand-off (the frames stay
        held and are counted as shutdown drops; the runtime frees the
        rings next), or "fallback" when repeated ring deaths hit
        ``ring_fault_limit`` (the frames are UN-held — they were never
        ticketed, so the dispatch-mode loop that takes over re-peeks
        and serves them; nothing is dropped by the mode switch
        itself)."""
        tp0 = time.perf_counter()
        # rx-enqueue stamp (ISSUE 11): taken at pack start so the
        # device-side wire-latency histogram covers pack + submit
        # queueing + window fill + ring backpressure — the whole host
        # leg up to the dispatch the governor (ROADMAP item 3) can
        # actually influence. 0 (unstamped) with telemetry off.
        stamp_us = 0
        if getattr(self.dp, "_tel_mode", "off") != "off":
            from vpp_tpu.ops.telemetry import tel_clock_us

            stamp_us = tel_clock_us()
        flat = np.zeros((PACKED_IN_ROWS, VEC), np.int32)
        non_ip = np.zeros(VEC, np.uint8)
        self._pack_group(frames, flat, non_ip)
        self.stats["t_pack"] += time.perf_counter() - tp0
        t0 = time.perf_counter()
        while True:
            try:
                self._ppump.submit(flat, now=self.dp.clock_ticks(),
                                   stamp_us=stamp_us,
                                   priority=priority)
                if self._ring_backoff.attempt:
                    self._ring_backoff.reset()
                break
            except RuntimeError:
                self._ring_faults += 1
                log.exception("resident loop died (ring fault %d%s)",
                              self._ring_faults,
                              f"/{self.ring_fault_limit}"
                              if self.ring_fault_limit else "")
                self.stats["batch_errors"] += 1
                # fold the dead ring's counters before replacing it, or
                # the exported ring_windows/ring_frames totals would
                # jump backwards (a spurious counter reset for scrapers)
                self._ring_fold(self._ppump)
                self._ppump = None
                if self.ring_fault_limit and \
                        self._ring_faults >= self.ring_fault_limit:
                    self._untake_any(frames, priority, tenant)
                    return "fallback"
                time.sleep(self._ring_backoff.next())
                try:
                    self._persist_start()
                except Exception:  # noqa: BLE001 — a relaunch that
                    # cannot even start IS the wedged-ring case the
                    # fallback exists for, whatever the limit says
                    log.exception("resident loop relaunch failed")
                    self._untake_any(frames, priority, tenant)
                    return "fallback"
        self.stats["t_dispatch"] += time.perf_counter() - t0
        # unlocked: the dispatch thread is _seq's only writer, so its
        # own read needs no lock; increments publish under _done_cv
        item = (self._seq, self._ppump, [frames], non_ip.view(bool), t0,
                priority)
        self._inflight_inc()
        while True:
            try:
                self._persist_q.put(item, timeout=0.05)
                break
            except queue.Full:
                if self._stop.is_set():
                    self._inflight_dec()
                    with self._lat_lock:
                        self.stats["drops_shutdown"] += sum(
                            f.n for f in frames)
                    return "stop"
        # under _done_cv for the same reason as the dispatch-mode bump:
        # the writer's shutdown gate reads _seq under the cv
        with self._done_cv:
            self._seq += 1
        self.stats["batches"] += 1
        self.stats["max_coalesce"] = max(self.stats["max_coalesce"],
                                         len(frames))
        return "ok"

    def _persist_dispatch_loop(self) -> None:
        rx = self.rings.rx
        hold_cap = max(2, rx.ring.n_slots - 4)
        try:
            # INSIDE the try: a failed resident-loop launch (device
            # unavailable, compile error) must still set the
            # dispatch-done gate in the finally, or the collector —
            # whose exit requires it — would spin forever and stop()'s
            # unbounded join would hang
            if self._ppump is None:  # warm() may have launched it
                self._persist_start()
            while not self._stop.is_set():
                if self.dp.epoch != self._persist_epoch:
                    self._persist_restart()
                self._governor_tick()
                # refill burst: compact pending frames into VEC-packet
                # descriptor slots and keep up to max_inflight slots
                # (or the governor's tighter in-flight depth) queued
                # at the ring stager before sleeping — whole windows
                # then ship with one transfer each, and the device
                # never idles between windows (the overlap discipline
                # of the r6 ladder, now at window granularity)
                gov = self.governor
                g_infl = self.max_inflight
                if gov is not None:
                    _f, g_infl, _shed = gov.limits()
                    g_infl = min(self.max_inflight, g_infl)
                burst = 0
                while not self._stop.is_set():
                    # express lane first (ISSUE 13): priority frames
                    # jump the bulk queue entirely — a lone-slot
                    # submit whose window the stager ships at once
                    self._scan_express(rx, hold_cap)
                    eg = self._take_express(rx)
                    if eg is not None:
                        st = self._persist_submit_group(eg,
                                                        priority=True)
                        if st == "stop":
                            return
                        if st == "fallback":
                            self._persist_fallback()
                            return
                        burst += 1
                        continue
                    with self._lat_lock:
                        infl = self.stats["inflight"]
                    if infl >= g_infl:
                        break  # governed depth: outer loop re-ticks
                    tenant = None
                    if self.tenants is not None:
                        # tenant lanes (ISSUE 14): shed from the hog
                        # before serving, then WFQ-take one
                        # single-tenant VEC-compacted group
                        if gov is not None and \
                                not gov.admit(False, self._backlog()):
                            if self._shed_tenant(rx):
                                continue
                        taken = self._take_tenant_group(rx,
                                                        max_pkts=VEC)
                        if taken is None:
                            break
                        tenant, tg = taken
                        groups = tg
                    else:
                        groups = self._take_groups(rx, hold_cap, 1,
                                                   max_pkts=VEC)
                        if not groups:
                            break
                        if gov is not None and \
                                not gov.admit(False, self._backlog()):
                            # brownout: bulk beyond the SLO's queue
                            # budget is dropped at admission,
                            # attributed — a shed costs no device trip
                            self._shed_group(groups)
                            continue
                    st = self._persist_submit_group(groups[0],
                                                    tenant=tenant)
                    if st == "stop":
                        return
                    if st == "fallback":
                        self._persist_fallback()
                        return
                    burst += 1
                    if burst >= g_infl:
                        break
                if burst == 0:
                    # idle: a ring death with nothing left to submit
                    # would otherwise never be counted (frames compact
                    # into few submits, and the death lands AFTER the
                    # last successful one) — poll the ring's health so
                    # the fault ladder advances regardless
                    if self._ring_check() == "fallback":
                        self._persist_fallback()
                        return
                    time.sleep(self.poll_s)
        finally:
            # signal the collector FIRST: every _persist_q.put this
            # thread will ever issue has happened, so Empty+done is a
            # race-free exit condition (ADVICE r5 shutdown race) —
            # then exit the device program (a resident loop left
            # behind would block the device for every later user)
            self._persist_dispatch_done.set()
            try:
                self._persist_stop_merge()
            except Exception:  # noqa: BLE001 — shutdown path
                log.exception("persistent loop shutdown failed")

    def _ring_check(self) -> str:
        """Advance the ring-fault ladder off a DEAD-but-idle resident
        ring (dispatch-thread only). Returns "fallback" once the limit
        is hit (or a relaunch cannot even start), else "ok" with a
        healthy — possibly freshly relaunched — ring in place."""
        pp = self._ppump
        if pp is None or not pp.failed:
            return "ok"
        self._ring_faults += 1
        log.error("resident loop dead at idle (ring fault %d%s)",
                  self._ring_faults,
                  f"/{self.ring_fault_limit}"
                  if self.ring_fault_limit else "")
        self.stats["batch_errors"] += 1
        self._ring_fold(pp)
        self._ppump = None
        if self.ring_fault_limit and \
                self._ring_faults >= self.ring_fault_limit:
            return "fallback"
        time.sleep(self._ring_backoff.next())
        try:
            self._persist_start()
        except Exception:  # noqa: BLE001 — same rule as the submit
            # path: a relaunch that cannot start IS the wedged ring
            log.exception("resident loop relaunch failed")
            return "fallback"
        return "ok"

    def _persist_fallback(self) -> None:
        """Degraded-mode escape hatch (ISSUE 8): the resident device
        ring died ``ring_fault_limit`` times, so stop relaunching it
        and serve traffic through the dispatch ladder instead — slower
        (per-batch host round trips come back) but alive. Runs ON the
        persist dispatch thread, which simply becomes the dispatch-mode
        dispatch thread; the missing piece of the dispatch topology
        (the concurrent fetch workers) is started here. Frames the
        failed submit un-held are re-peeked by the ladder, and tickets
        already in the collector's FIFO resolve as attributed
        ``drops_error`` — the mode switch itself loses nothing.

        One-way: the ring path stays off until the process restarts.
        ``degraded_ring`` drives ``vpp_tpu_degraded{component="ring"}``
        and `show resilience`; the first ladder dispatch pays its jit
        compile inline (logged) — the degraded mode trades a one-time
        stall for not being wedged."""
        log.error("device ring failed %d times — falling back to "
                  "dispatch mode (degraded; first ladder dispatch "
                  "compiles inline)", self._ring_faults)
        self.degraded_ring = True
        self.mode = "dispatch"
        # NOTE: ICMP error generation stays off — persistent mode
        # zeroed icmp_src_ip at construction (self.icmp is None), so
        # the dispatch topology taken over here has no error path to
        # start; re-enabling it would need the agent to rebuild the
        # pump
        # no further ring tickets will ever be issued: let the
        # collector drain what is queued and idle until stop()
        self._persist_dispatch_done.set()
        for i in range(self.workers):
            t = threading.Thread(target=self._fetch_loop, daemon=True,
                                 name=f"dp-pump-fetch{i}")
            t.start()
            self._threads.append(t)
        self._dispatch_loop()

    def sync_sessions(self, timeout: float = 30.0) -> bool:
        """Persistent mode: graft a consistent device COPY of the
        in-ring session state into dp.tables (ISSUE 8). The resident
        ring threads its tables privately and only merges them back at
        stop/epoch-restart — without this hook a long-lived ring
        leaves dp.tables frozen at launch state, so the maintenance
        consumers (the crash-consistent snapshotter above all, but
        also occupancy gauges and bulk expiry) would serve stale
        sessions against an advancing clock. Returns True when fresh
        state landed; False (no ring, dead ring, timeout) means the
        caller proceeds with whatever dp.tables already holds — never
        worse than before the hook existed. Any thread may call it;
        the copy itself happens on the ring's stager at a window
        boundary (PersistentPump.checkpoint_sessions)."""
        pp = self._ppump
        if self.mode != "persistent" or pp is None:
            return False
        try:
            sess = pp.checkpoint_sessions(timeout=timeout)
        except RuntimeError:
            return False
        if sess is None:
            return False
        with self.dp._lock:
            if self.dp.tables is None:
                return False
            self.dp.tables = self.dp.tables._replace(**sess)
            # the grafted state carries stamps up to the ring's latest
            # submit clock — advance the dataplane's session clock to
            # match so a snapshot's rebase origin is consistent
            self.dp._now = max(self.dp._now, self.dp.clock_ticks())
        return True

    def tel_snapshot(self) -> Optional[dict]:
        """Collect-facing device-telemetry snapshot (ISSUE 11). In
        persistent mode this unpacks the latest ring rider — the
        telemetry planes that rode the last window's ONE result fetch
        — so collect never touches the ring's private tables carry
        (and never makes a device transfer at all). Other modes (and
        a ring that hasn't written back yet) fall through to the
        dataplane's own small-plane fetch. None when telemetry is
        off."""
        tel_mode = getattr(self.dp, "_tel_mode", "off")
        if tel_mode == "off":
            return None
        pp = self._ppump
        if self.mode == "persistent" and pp is not None:
            raw = pp.tel_raw()
            if raw is not None:
                from vpp_tpu.ops.telemetry import unpack_tel_rider
                from vpp_tpu.pipeline.tables import tel_capacity

                nb, _d, _w, k = tel_capacity(self.dp.config)
                snap = unpack_tel_rider(raw, nb, k)
                snap["mode"] = tel_mode
                snap["bins"] = np.asarray(snap["bins"], np.int64)
                snap["top_cnt"] = np.asarray(snap["top_cnt"], np.int64)
                return snap
        return self.dp.telemetry_snapshot()

    def _ring_fold(self, pp) -> None:
        """Retire a PersistentPump's monotonic ring counters into the
        accumulator EXACTLY ONCE, so restarts (epoch swaps,
        death-relaunches) never reset the exported totals. The
        retired flag flips under _lat_lock — the same lock
        _ring_stats_sync holds while deciding whether to add the
        ring's live counters — so a sync racing this fold either sees
        the ring un-retired (adds live, accumulator without it) or
        retired (accumulator only): never both."""
        if pp is None:
            return
        snap = pp.stats_snapshot()
        with self._lat_lock:
            if pp.retired:
                return
            pp.retired = True
            for k in self._ring_accum:
                self._ring_accum[k] += int(snap.get(k, 0))

    def _ring_stats_sync(self) -> None:
        """Refresh the public ring telemetry keys: accumulated counts
        from retired rings (epoch restarts) plus the live ring's
        counters. Host scalars only — nothing crosses the device
        transport (the PR 6 `show sessions` rule)."""
        pp = self._ppump
        live = pp.stats_snapshot() if pp is not None else {}
        with self._lat_lock:
            if pp is not None and pp.retired:
                live = {}  # already folded into the accumulator
            for k in self._ring_accum:
                self.stats[k] = self._ring_accum[k] + int(live.get(k, 0))
            self.stats["ring_inflight"] = int(live.get("ring_inflight", 0))
            self.stats["ring_lag"] = int(live.get("ring_lag", 0))

    def _persist_collect_one(self, item) -> None:
        seq, ppump, groups, non_ip, t0, pri = item
        tf0 = time.perf_counter()
        batch = None
        fast = False
        deadline = time.monotonic() + 300.0
        # NOT gated on _stop: an already-submitted frame's result
        # is coming (PersistentPump.stop drains every queued frame
        # before the loop exits) — discarding it at pump shutdown
        # would silently drop live traffic the dispatch mode
        # delivers. Loop-death/timeout still bounds the wait.
        while True:
            try:
                batch, aux = ppump.result_ex(timeout=0.2)
                fast = self._account_fastpath(aux)
                break
            except queue.Empty:
                if time.monotonic() > deadline:
                    log.error("resident loop result timed out")
                    self.stats["batch_errors"] += 1
                    break
            except RuntimeError:
                log.exception("resident loop result failed")
                self.stats["batch_errors"] += 1
                break
        with self._lat_lock:
            self.stats["t_fetch"] += time.perf_counter() - tf0
            if batch is None:
                # the frames will be released unwritten by the writer:
                # attribute the loss. The ring drains every queued
                # frame at stop(), so a missing result is a loop
                # death / timeout — reason "error", even mid-shutdown
                # (labeling it "shutdown" would hide a real failure)
                self.stats["drops_error"] += sum(
                    f.n for g in groups for f in g)
        self._ring_stats_sync()
        with self._done_cv:
            self._done[seq] = (batch, groups, non_ip, t0, fast, pri)
            self._done_cv.notify_all()

    def _persist_collect_loop(self) -> None:
        """Pull ordered results off the resident loop and hand them to
        the in-order tx writer. The loop preserves submission order, so
        seq mapping is one FIFO deep — no reorder buffer needed, but
        the writer's _done contract is kept so `stop()` semantics and
        stats stay identical across modes. Exit only once the
        dispatcher is DONE and the hand-off queue is drained: an
        Empty+_stop exit races a dispatcher mid-put, orphaning a seq
        the writer would spin on forever (ADVICE r5)."""
        while True:
            try:
                item = self._persist_q.get(timeout=0.05)
            except queue.Empty:
                if (self._stop.is_set()
                        and self._persist_dispatch_done.is_set()):
                    # final drain: the dispatcher has exited, so
                    # anything it ever queued is already visible here
                    while True:
                        try:
                            item = self._persist_q.get_nowait()
                        except queue.Empty:
                            return
                        self._persist_collect_one(item)
                continue
            self._persist_collect_one(item)

    # --- fetch workers: concurrent device_get (RPC round trips) ---
    def _fetch_loop(self) -> None:
        with self._lat_lock:
            self._fetchers_live += 1
        try:
            while True:
                # express first (ISSUE 13): a priority batch's fetch
                # waits only for the fetch in progress, never behind
                # the queued bulk FIFO
                try:
                    item = self._inflight_pri.get_nowait()
                except queue.Empty:
                    try:
                        item = self._inflight.get(timeout=0.05)
                    except queue.Empty:
                        if self._stop.is_set():
                            return
                        continue
                if item is _SENTINEL:
                    # wake the next worker too, then exit
                    try:
                        self._inflight.put_nowait(_SENTINEL)
                    except queue.Full:
                        pass
                    return
                self._complete_item(item)
        finally:
            with self._lat_lock:
                self._fetchers_live -= 1

    def _complete_item(self, item) -> None:
        """Fetch one dispatched batch's device result and hand it to
        the in-order writer (the fetch-worker body; the writer's
        shutdown rescue path reuses it for batches stranded behind the
        stop sentinel)."""
        import jax

        seq, payload, groups, non_ip, t0, slow, pri = item
        delay = self._fetch_delay
        if delay is not None:
            time.sleep(delay(seq) if callable(delay) else delay)
        fast = False
        try:
            # faults: "pump.fetch" = the device result fetch failing
            # (transport error, wedged tunnel) — exercises the
            # drops_error attribution + in-order release path
            faults.fire("pump.fetch")
            if slow:
                out_pkts, disp, tx_if, next_hop, cause = jax.device_get(
                    (payload.pkts, payload.disp, payload.tx_if,
                     payload.next_hop, payload.drop_cause)
                )
                count_device_transfer(
                    "pump.fetch.columns",
                    (out_pkts, disp, tx_if, next_hop, cause))
                batch = {
                    "src_ip": np.asarray(out_pkts.src_ip),
                    "dst_ip": np.asarray(out_pkts.dst_ip),
                    "proto": np.asarray(out_pkts.proto),
                    "sport": np.asarray(out_pkts.sport),
                    "dport": np.asarray(out_pkts.dport),
                    "ttl": np.asarray(out_pkts.ttl),
                    "pkt_len": np.asarray(out_pkts.pkt_len),
                    "disp": np.asarray(disp).astype(np.int32).copy(),
                    "tx_if": np.asarray(tx_if).astype(np.int32).copy(),
                    "next_hop": np.asarray(next_hop),
                    "drop_cause": np.asarray(cause).astype(np.int32),
                }
            else:
                # ONE packed fetch ([5, B], or [K, 5, B] for a
                # chain fold), kept PACKED: the tx writer decodes
                # it straight into ring slots natively
                # (rings.push_packed), no host-side column arrays.
                # The wait (device compute / tunnel RTT) is timed
                # apart from the copy: the wait overlaps the other
                # in-flight batches across the fetch pool, so only
                # the copy is a serial throughput cost.
                # np.array: device_get may hand back a zero-copy
                # view of a device buffer whose lifetime ends with
                # `payload` — the copy (20 B/packet) outlives it
                out, aux = payload  # aux: [3] (or [K,3]) tier summary
                tw0 = time.perf_counter()
                jax.block_until_ready(payload)
                tf0 = time.perf_counter()
                # one fetch for both: the aux summary (12 B) must not
                # cost a second round trip on a remote transport
                out_h, aux_h = jax.device_get((out, aux))
                count_device_transfer("pump.fetch.packed", (out_h, aux_h))
                batch = np.array(out_h)
                tf1 = time.perf_counter()
                # concurrent fetchers: accumulate under a lock or
                # the += load/add/store interleaves and undercounts
                with self._lat_lock:
                    self.stats["t_fetch_wait"] += tf0 - tw0
                    self.stats["t_fetch"] += tf1 - tf0
                fast = self._account_fastpath(aux_h)
        except Exception:
            log.exception("pump fetch failed (batch %d)", seq)
            batch = None
            self.stats["batch_errors"] += 1
            with self._lat_lock:
                # the writer releases these frames unwritten —
                # attribute the loss, don't just count a batch error
                self.stats["drops_error"] += sum(
                    f.n for g in groups for f in g)
        with self._done_cv:
            self._done[seq] = (batch, groups, non_ip, t0, fast, pri)
            self._done_cv.notify_all()

    def _account_fastpath(self, aux) -> bool:
        """Fold one dispatch's ``[PACKED_AUX_ROWS]`` (or chain-fold
        ``[K, PACKED_AUX_ROWS]``) aux summary into the pump counters;
        returns True when EVERY sub-batch ran the classify-free kernel
        (the whole dispatch's latency then belongs to the fast-tier
        histogram). Row meanings come from
        ``pipeline.dataplane.PACKED_AUX_SCHEMA`` — the width
        authority; the ``a.shape[1] >=`` guards keep older/narrower
        riders (mesh pumps, test fakes) accounting their prefix.

        ``fastpath_batches`` counts at DISPATCH granularity — a chain
        fold counts once, and only when all K sub-batches went fast —
        so it stays directly comparable to ``stats["batches"]`` (the
        ratio is a true fraction). Partial folds still show up in the
        packet-level hits/alive accumulators. Rows 3/4 carry the
        session-table pressure counters (insert election losses,
        evictions), rows 5-7 the ML-stage verdict counters (scored /
        flagged / dropped), rows 8/9 the device-telemetry counters
        (wire latencies histogrammed / packets sketched) when the
        program provides them."""
        if aux is None:
            return False
        a = np.asarray(aux)
        if a.ndim == 1:
            a = a[None, :]
        all_fast = bool((a[:, 0] > 0).all())
        with self._lat_lock:
            if all_fast:
                self.stats["fastpath_batches"] += 1
            self.stats["fastpath_alive"] += int(a[:, 1].sum())
            self.stats["fastpath_hits"] += int(a[:, 2].sum())
            if a.shape[1] >= 5:
                self.stats["sess_insert_fails"] += int(a[:, 3].sum())
                self.stats["sess_evictions"] += int(a[:, 4].sum())
            if a.shape[1] >= 8:
                self.stats["ml_scored"] += int(a[:, 5].sum())
                self.stats["ml_flagged"] += int(a[:, 6].sum())
                self.stats["ml_drops"] += int(a[:, 7].sum())
            if a.shape[1] >= 10:
                self.stats["tel_observed"] += int(a[:, 8].sum())
                self.stats["tel_sketched"] += int(a[:, 9].sum())
            if a.shape[1] >= 12:
                # tenancy rows (ISSUE 14): device token-bucket drops
                # feed the tenant_quota reason of
                # vpp_tpu_pump_drops_total; slice insert failures are
                # the per-tenant congestion counter
                self.stats["drops_tenant_quota"] += int(a[:, 10].sum())
                self.stats["tenant_sess_quota_fails"] += \
                    int(a[:, 11].sum())
        return all_fast

    # --- tx writer: reorder, split, write tx ring, release rx slots ---
    def _write_loop(self) -> None:
        next_seq = 0
        # seqs already written OUT of dispatch order by the express
        # jump below — consumed (skipped) when next_seq reaches them
        skipped: set = set()
        while True:
            rescue = False
            item = None
            with self._done_cv:
                while True:
                    while next_seq in skipped:
                        skipped.discard(next_seq)
                        next_seq += 1
                    if next_seq in self._done:
                        item = self._done.pop(next_seq)
                        next_seq += 1
                        break
                    # express jump (ISSUE 13): a completed PRIORITY
                    # item is written immediately, ahead of earlier
                    # bulk seqs still fetching — legal because rx
                    # release order is rid-based (_release_done), so
                    # only the tx write order changes, and reflex
                    # frames must not wait out the bulk pipeline
                    ex = min((s for s, it in self._done.items()
                              if it[5]), default=None)
                    if ex is not None:
                        item = self._done.pop(ex)
                        skipped.add(ex)
                        break
                    # exit once stopped and every dispatched batch has
                    # been written (_seq is the dispatch count; the
                    # sentinel may still sit in _inflight, so emptiness
                    # of the queue is NOT a usable signal here)
                    if self._stop.is_set() and next_seq >= self._seq:
                        return
                    if self._stop.is_set() and \
                            not (self._inflight.empty()
                                 and self._inflight_pri.empty()):
                        with self._lat_lock:
                            fetchers = self._fetchers_live
                        if fetchers == 0:
                            # stop() raced _dispatch's put: a batch
                            # landed BEHIND the stop sentinel and every
                            # fetch worker has already exited — without
                            # a rescue its seq never reaches _done and
                            # this unbounded-join loop hangs forever
                            rescue = True
                            break
                    self._done_cv.wait(timeout=0.05)
            if rescue:
                # complete stranded batches on this thread (outside
                # _done_cv — _complete_item takes it to post results)
                for q in (self._inflight_pri, self._inflight):
                    while True:
                        try:
                            stranded = q.get_nowait()
                        except queue.Empty:
                            break
                        if stranded is not _SENTINEL:
                            self._complete_item(stranded)
                continue
            try:
                self._write(*item)
            except Exception:
                log.exception("pump tx write failed")
                self._release_done(item[1])
            self._inflight_dec()

    def _write_packed_group(self, batch: np.ndarray, frames: list,
                            host_if: int, epoch: int,
                            icmp_on: bool) -> None:
        """Fast path for one coalesce group: ONE native call per frame
        decodes the packed [5, B] result straight into a reserved tx
        slot (pass-through columns from the rx slot, non-IP punt
        applied in C)."""
        off = 0
        for f in frames:
            n = f.n
            with self._tx_lock:
                try:
                    # faults: "pump.tx_push" = a stalled tx ring (the
                    # consumer stopped draining) — the frame takes the
                    # drops_tx_stall path exactly like a full ring
                    faults.fire("pump.tx_push")
                    ok = self.rings.tx.push_packed(batch, off, n, f,
                                                   host_if, epoch,
                                                   self._cause)
                except faults.FaultInjected:
                    ok = False
            if ok:
                self.stats["frames"] += 1
                self.stats["pkts"] += n
                if icmp_on and n and self._cause[:n].any():
                    self._emit_icmp_frame(f, self._cause)
            else:
                self.stats["tx_ring_full"] += 1
                self.stats["drops_tx_stall"] += n
            off += n

    def _write(self, batch, groups: list, non_ip, t0: float,
               fast: bool = False, pri: bool = False) -> None:
        if isinstance(batch, np.ndarray):
            tw0 = time.perf_counter()
            host_if = (self.dp.host_if
                       if self.dp.host_if is not None else -1)
            epoch = self.dp.epoch
            icmp_on = self.icmp is not None
            if batch.ndim == 3:
                # chain fold: sub-batch k carries group k's packets
                # (padded stack rows past len(groups) hold no frames)
                for k, frames in enumerate(groups):
                    self._write_packed_group(batch[k], frames, host_if,
                                             epoch, icmp_on)
            else:
                self._write_packed_group(batch, groups[0], host_if,
                                         epoch, icmp_on)
            self.stats["t_write"] += time.perf_counter() - tw0
            lat = time.perf_counter() - t0
            with self._lat_lock:
                self.batch_lat.append(lat)
                if pri:
                    self.pri_lat.append(lat)
                    self._pri_total += 1
            if self.latency_hist is not None:
                self.latency_hist.observe(lat)
            if fast and self.fastpath_hist is not None:
                self.fastpath_hist.observe(lat)
        elif batch is not None:
            # tracing path: full column dict from the unpacked step
            # (the tracer never chains, so there is exactly one group)
            frames = groups[0]
            if non_ip is not None and non_ip.any():
                host_if = (self.dp.host_if
                           if self.dp.host_if is not None else -1)
                batch["disp"][non_ip] = int(Disposition.HOST)
                batch["tx_if"][non_ip] = host_if
            # error-drop attribution is pump-consumed (ICMP error
            # generation), not a ring column
            drop_cause = batch.pop("drop_cause", None)
            batch["rx_if"] = batch.pop("tx_if")  # tx direction: egress if
            epoch = self.dp.epoch
            off = 0
            for f in frames:
                n = f.n
                out_cols = {}
                for name, arr in batch.items():
                    col = np.zeros(VEC, arr.dtype)
                    col[:n] = arr[off:off + n]
                    out_cols[name] = col
                out_cols["flags"] = f.cols["flags"]  # valid+non-ip4
                out_cols["meta"] = f.cols["meta"]
                # pipeline-invariant fields don't travel back over the
                # packed boundary; the rx slot is their source of truth
                # (the tracing path still returns them — don't clobber)
                for inv in ("proto", "pkt_len"):
                    if inv not in out_cols:
                        out_cols[inv] = f.cols[inv]
                with self._tx_lock:
                    ok = self.rings.tx.push(out_cols, n,
                                            payload=f.payload,
                                            epoch=epoch)
                if ok:
                    self.stats["frames"] += 1
                    self.stats["pkts"] += n
                    # ICMP only for frames that made it out: under tx
                    # backpressure the error frames drop with the
                    # traffic (same policy as the fast path)
                    if self.icmp is not None and drop_cause is not None:
                        cause = np.zeros(VEC, np.int32)
                        cause[:n] = drop_cause[off:off + n]
                        if cause[:n].any():
                            self._emit_icmp_frame(f, cause)
                else:
                    self.stats["tx_ring_full"] += 1
                    self.stats["drops_tx_stall"] += n
                off += n
            lat = time.perf_counter() - t0
            with self._lat_lock:
                self.batch_lat.append(lat)
                if pri:
                    self.pri_lat.append(lat)
                    self._pri_total += 1
            if self.latency_hist is not None:
                self.latency_hist.observe(lat)
        self._release_done(groups)

    def _emit_icmp_frame(self, f, cause: np.ndarray) -> None:
        """Generate ICMP time-exceeded / net-unreachable frames for one
        rx frame's attributed drops (VERDICT r3 Next #8; VPP
        ip4-icmp-error). The invoking packet is quoted from its rx slot
        payload — still ring-owned here, so the original bytes are
        stable. ``cause`` is the per-packet DROP_* array [VEC].

        The errors are ROUTED THROUGH THE PIPELINE like any ingress
        packet (rx on the node's host interface — they originate from
        the vswitch itself), exactly as VPP's ip4-icmp-error node feeds
        back into ip4-lookup: errors toward local pods deliver on the
        pod interface, errors toward REMOTE senders (the invoking
        packet arrived on the uplink) pick up the route's next_hop and
        leave VXLAN-encapsulated — cross-node traceroute works."""
        from vpp_tpu.io.icmp import classify_drops

        ingress = self.dp.host_if
        if ingress is None:
            ingress = self.dp.uplink_if
        if ingress is None:
            return  # no self-originated ingress point configured
        n = f.n
        idxs, types = classify_drops(cause, f.cols["flags"],
                                     f.cols["ttl"], n)
        if not len(idxs):
            return
        built = self.icmp.build_frame(
            idxs, types, f.cols, f.payload, self._icmp_scratch,
            rx_if=int(ingress),
        )
        if built is None:
            return
        out_cols, k = built
        # hand off to the dedicated error-path thread: the classify is
        # a blocking device round trip (~100 ms on a remote transport)
        # and this is the IN-ORDER tx writer — blocking here would
        # head-of-line-block all forwarded traffic and stall rx slot
        # releases. Payload rows are copied because _icmp_scratch is
        # reused for the next build.
        try:
            self._icmp_q.put_nowait(
                (out_cols, k, self._icmp_scratch[:k].copy())
            )
        except queue.Full:
            self.icmp.suppressed += k

    def _icmp_loop(self) -> None:
        """Error-path worker: routes built ICMP error frames through
        the device pipeline (rx on the host interface — VPP's
        ip4-icmp-error feeding ip4-lookup) and pushes the verdicts to
        the tx ring. Its blocking round trips never touch the
        forwarding threads."""
        import jax

        from vpp_tpu.native.pktio import flatten_cols
        from vpp_tpu.native.ring import RING_COLUMNS
        from vpp_tpu.pipeline.dataplane import packed_input_zeros

        payload_buf = np.zeros((VEC, self.rings.tx.snap), np.uint8)
        while not self._stop.is_set():
            try:
                out_cols, k, payload = self._icmp_q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                flat = packed_input_zeros(VEC)
                pack_packet_columns(flat.view(np.uint32), out_cols, k)
                # the verdict assigns the real egress + next_hop.
                # commit=False: error classification must not install
                # sessions NOR race the dispatch thread's table
                # commits (two committers would drop one side's
                # reflective-session installs)
                res = np.array(jax.device_get(
                    self.dp.process_packed(flat, commit=False)
                ))
                block = flatten_cols(out_cols)
                cols_view = {
                    name: block[j]
                    for j, (name, _dt) in enumerate(RING_COLUMNS)
                }
                payload_buf[:k] = payload
                frame = _IcmpFrame(cols=cols_view, n=k,
                                   epoch=self.dp.epoch,
                                   payload=payload_buf)
                host_if = (self.dp.host_if
                           if self.dp.host_if is not None else -1)
                with self._tx_lock:
                    ok = self.rings.tx.push_packed(res, 0, k, frame,
                                                   host_if,
                                                   self.dp.epoch,
                                                   self._icmp_cause)
                if ok:
                    self.stats["icmp_errors"] = (
                        self.stats.get("icmp_errors", 0) + k
                    )
                else:
                    self.stats["tx_ring_full"] += 1
            except Exception:
                log.exception("icmp error path failed")

    # --- observability ---
    def reset_latency(self) -> None:
        """Clear the latency window so the next ``latency_us()``
        covers only batches from here on (the bench scopes each paced
        round this way)."""
        with self._lat_lock:
            self.batch_lat.clear()
            self.pri_lat.clear()

    def latency_us(self) -> dict:
        """p50/p99 dispatch→tx batch latency over the recent window."""
        with self._lat_lock:
            snap = list(self.batch_lat)
        if not snap:
            return {"p50": 0.0, "p99": 0.0, "n": 0}
        arr = np.asarray(snap) * 1e6
        return {
            "p50": float(np.percentile(arr, 50)),
            "p99": float(np.percentile(arr, 99)),
            "n": int(arr.size),
        }
