"""Host-side ICMP error generation (time-exceeded / unreachable).

Reference analog: VPP's ip4 error path — `error-drop` is only one
branch of the graph; TTL-expired packets branch to ip4-icmp-error and
emit ICMP time-exceeded, FIB misses emit net-unreachable
(/root/reference/docs/VPP_PACKET_TRACING_K8S.md:28-50 shows the chain;
pod `traceroute` depends on the time-exceeded hop). The device
pipeline attributes every drop (graph.py DROP_*, carried across the
packed boundary); this module turns the attributed drops into ICMP
error frames on the tx ring — an error path belongs on the host CPU,
not in the packet-rate device program.

RFC 792 format: IP header (src = this vswitch's gateway address) +
8-byte ICMP header + the invoking packet's IP header + first 8 L4
bytes. Token-bucket rate-limited like VPP's ICMP error throttling.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import numpy as np

ICMP_TIME_EXCEEDED = 11   # code 0: TTL expired in transit
ICMP_UNREACHABLE = 3      # code 0: net unreachable
ETH_HDR = 14
_IP_HDR = 20
_ICMP_HDR = 8


def _checksum(data: np.ndarray) -> int:
    """RFC 1071 internet checksum of a uint8 array (even length pads)."""
    if data.size % 2:
        data = np.concatenate([data, np.zeros(1, np.uint8)])
    words = data.reshape(-1, 2).astype(np.uint32)
    s = int((words[:, 0] * 256 + words[:, 1]).sum())
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return (~s) & 0xFFFF


def build_icmp_error(
    icmp_type: int,
    src_ip: int,
    orig_frame: np.ndarray,
    orig_len: Optional[int] = None,
) -> Optional[Tuple[np.ndarray, int]]:
    """One ICMP error frame quoting ``orig_frame`` (the invoking packet
    as received, Ethernet included). ``orig_len`` is the invoking
    packet's L3 length — the quote must never read past it: payload
    rows are ring slots copied only up to each frame's wire length, so
    bytes beyond the packet are leftovers from a previous ring lap
    (another flow's data — quoting them would leak it to the sender).
    Returns (frame bytes with MAC-less Ethernet header, pkt_len) or
    None when the original is not a quotable IPv4 packet. pkt_len is
    the L3 length (wire = +14)."""
    if orig_frame.shape[0] < ETH_HDR + _IP_HDR:
        return None
    oip = orig_frame[ETH_HDR:]
    if (int(oip[0]) >> 4) != 4:
        return None
    oihl = (int(oip[0]) & 0xF) * 4
    avail = oip.shape[0]
    if orig_len is not None:
        avail = min(avail, max(int(orig_len), 0))
    # RFC 792/1122: never generate an ICMP error about an ICMP error
    # (types 3/4/5/11/12) — an undeliverable error must die silently,
    # not ping-pong more errors through the data plane. The type byte
    # is read only within the packet's REAL length (bytes past
    # orig_len are another flow's residue from a previous ring lap);
    # an ICMP packet whose type byte is unreadable is conservatively
    # not quoted at all.
    if int(oip[9]) == 1:
        if oihl >= avail:
            return None
        if int(oip[oihl]) in (3, 4, 5, 11, 12):
            return None
    quote = min(oihl + 8, avail)
    if quote < _IP_HDR:
        return None
    orig_src = int.from_bytes(bytes(oip[12:16]), "big")
    total = _IP_HDR + _ICMP_HDR + quote

    frame = np.zeros(ETH_HDR + total, np.uint8)
    # MACs are filled by the tx dispatch (neighbor table + egress
    # interface); the EtherType is ours to set — a zero type field
    # would be silently ignored by the receiving kernel
    frame[12] = 0x08
    frame[13] = 0x00
    ip = frame[ETH_HDR:]
    ip[0] = 0x45
    ip[2:4] = np.frombuffer(total.to_bytes(2, "big"), np.uint8)
    ip[8] = 64                      # ttl
    ip[9] = 1                       # proto ICMP
    ip[12:16] = np.frombuffer(int(src_ip).to_bytes(4, "big"), np.uint8)
    ip[16:20] = np.frombuffer(orig_src.to_bytes(4, "big"), np.uint8)
    ck = _checksum(ip[:_IP_HDR])
    ip[10:12] = np.frombuffer(ck.to_bytes(2, "big"), np.uint8)

    icmp = ip[_IP_HDR:]
    icmp[0] = icmp_type             # code stays 0 for both types
    icmp[_ICMP_HDR:_ICMP_HDR + quote] = oip[:quote]
    ck = _checksum(icmp[: _ICMP_HDR + quote])
    icmp[2:4] = np.frombuffer(ck.to_bytes(2, "big"), np.uint8)
    return frame, total


def classify_drops(causes: np.ndarray, flags: np.ndarray,
                   ttl: np.ndarray, n: int):
    """Which attributed drops deserve an ICMP error, and which type:
    (idxs, types) over positions [0, n). DROP_IP4 covers TTL/len/bad-if
    — only a TTL of <= 1 at ingress is a time-exceeded; FIB misses are
    net-unreachable; every other cause (policy, fib-drop, NAT) stays
    silent. Shared by the single-node and cluster pumps so the
    cause→error mapping can never diverge between them."""
    from vpp_tpu.pipeline.graph import DROP_IP4, DROP_NO_ROUTE

    c = causes[:n]
    valid = (np.asarray(flags[:n]).view(np.int32) & 1) != 0
    t = np.asarray(ttl[:n]).view(np.int32)
    ttl_exp = (c == DROP_IP4) & (t <= 1) & valid
    no_rt = (c == DROP_NO_ROUTE) & valid
    idxs = np.nonzero(ttl_exp | no_rt)[0]
    types = np.where(ttl_exp[idxs], ICMP_TIME_EXCEEDED, ICMP_UNREACHABLE)
    return idxs, types


class IcmpErrorGen:
    """Builds rate-limited ICMP error *frames* (ring columns + payload
    rows) for a batch of attributed drops."""

    def __init__(self, src_ip: int, vec: int, snap: int,
                 rate_per_s: float = 256.0):
        self.src_ip = int(src_ip)
        self.vec = vec
        self.snap = snap
        self.rate = float(rate_per_s)
        self._tokens = self.rate
        self._t_last = time.monotonic()
        self.emitted = 0
        self.suppressed = 0

    def _take(self, want: int) -> int:
        now = time.monotonic()
        self._tokens = min(
            self.rate, self._tokens + (now - self._t_last) * self.rate
        )
        self._t_last = now
        grant = min(want, int(self._tokens))
        self._tokens -= grant
        self.suppressed += want - grant
        return grant

    def build_frame(
        self, idxs: np.ndarray, types: np.ndarray, cols: Dict[str, np.ndarray],
        payload: np.ndarray, scratch: np.ndarray,
        rx_if: Optional[int] = None,
    ) -> Optional[Tuple[Dict[str, np.ndarray], int]]:
        """ICMP error frame for dropped packets ``idxs`` (positions in
        the ORIGINAL rx frame): ``cols``/``payload`` are that frame's
        ring columns + payload rows; ``scratch`` is a [VEC, snap] uint8
        payload buffer for the new frame. ``rx_if`` is the interface
        the error packets claim as INGRESS — self-originated traffic
        enters via the node's host interface and the caller routes it
        through the pipeline like any packet (VPP: ip4-icmp-error
        feeds ip4-lookup). Returns (ring columns, n) or None when rate
        limiting suppressed everything."""
        grant = self._take(len(idxs))
        if not grant:
            return None
        out = {
            name: np.zeros(self.vec, arr.dtype) for name, arr in cols.items()
        }
        n = 0
        for k, i in enumerate(idxs[:grant]):
            built = build_icmp_error(
                int(types[k]), self.src_ip, payload[i],
                orig_len=int(cols["pkt_len"][i]),
            )
            if built is None:
                continue
            frame, pkt_len = built
            scratch[n, : frame.shape[0]] = frame
            scratch[n, frame.shape[0]:] = 0
            out["src_ip"][n] = np.uint32(self.src_ip)
            out["dst_ip"][n] = cols["src_ip"][i]  # back to the sender
            out["proto"][n] = 1
            out["ttl"][n] = 64
            out["pkt_len"][n] = pkt_len
            out["rx_if"][n] = (
                rx_if if rx_if is not None else cols["rx_if"][i]
            )
            out["flags"][n] = 1  # FLAG_VALID
            out["meta"][n] = -1
            n += 1
        if not n:
            return None
        self.emitted += n
        return out, n
