"""Packet transports: how raw frames enter and leave the IO daemon.

Production transports are AF_PACKET (bind a kernel interface, the
af-packet-input analog) and TAP (/dev/net/tun, the tapcli-rx analog);
tests and unprivileged dev use SOCK_DGRAM socketpairs which preserve
frame boundaries. All expose fileno() so the daemon can select() across
every interface at once.

Reference: VPP's af_packet/tap drivers configured by the vswitch
(contiv-vswitch.conf:8-11, pod TAP/veth+af_packet builders
plugins/contiv/pod.go:262-360).
"""

from __future__ import annotations

import fcntl
import os
import socket
import struct
from typing import List, Optional, Tuple

ETH_P_ALL = 0x0003
TUNSETIFF = 0x400454CA
IFF_TAP = 0x0002
IFF_NO_PI = 0x1000
SIOCGIFHWADDR = 0x8927

BROADCAST_MAC = b"\xff\xff\xff\xff\xff\xff"


class Transport:
    """One packet endpoint (an "interface" of the data plane)."""

    name: str = ""
    mac: bytes = b"\x02\x00\x00\x00\x00\x00"

    @property
    def batch_fd(self) -> Optional[int]:
        """Socket fd usable with sendmmsg/recvmmsg (the native batch
        path, native/pkt_io.cpp), or None — TAP is a char device whose
        fd the mmsg syscalls reject, so it keeps the per-frame path."""
        return None

    def fileno(self) -> int:
        raise NotImplementedError

    def recv_frames(self, max_frames: int) -> List[bytes]:
        """Drain up to max_frames raw frames without blocking."""
        raise NotImplementedError

    def send_frame(self, frame: bytes) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


def _drain_fd_socket(sock: socket.socket, max_frames: int,
                     bufsize: int = 65535) -> List[bytes]:
    out: List[bytes] = []
    while len(out) < max_frames:
        try:
            data = sock.recv(bufsize)
        except BlockingIOError:
            break
        except OSError:
            break
        if not data:
            break
        out.append(data)
    return out


class AfPacketTransport(Transport):
    """Raw L2 socket bound to a kernel interface (requires CAP_NET_RAW)."""

    def __init__(self, ifname: str, rcvbuf: int = 64 << 20):
        self.name = ifname
        self.sock = socket.socket(
            socket.AF_PACKET, socket.SOCK_RAW, socket.htons(ETH_P_ALL)
        )
        # deep rx queue: the daemon drains in bursts (select → recvmmsg
        # batches) while sharing a core with the pump; the default
        # ~200 KB socket buffer drops entire line-rate bursts between
        # drains. RCVBUFFORCE pierces rmem_max under CAP_NET_ADMIN
        # (which af_packet needs anyway); fall back to the clamped set.
        SO_RCVBUFFORCE = 33
        try:
            self.sock.setsockopt(socket.SOL_SOCKET, SO_RCVBUFFORCE, rcvbuf)
        except OSError:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                 rcvbuf)
        # Never receive our OWN transmissions: without this every frame
        # the daemon sends on an interface is looped back into its rx
        # path (PACKET_OUTGOING), re-enters the pipeline, and — for
        # LOCAL-delivered traffic — re-transmits out the same interface
        # until TTL exhausts: ~60 wasted pipeline passes per delivered
        # packet, the dominant (hidden) cost of the r3 wire path.
        SOL_PACKET, PACKET_IGNORE_OUTGOING = 263, 23
        try:
            self.sock.setsockopt(SOL_PACKET, PACKET_IGNORE_OUTGOING, 1)
        except OSError:
            pass  # pre-4.20 kernel: loop suppressed by TTL only
        self.sock.bind((ifname, 0))
        self.sock.setblocking(False)
        info = fcntl.ioctl(
            self.sock.fileno(), SIOCGIFHWADDR,
            struct.pack("256s", ifname.encode()[:15]),
        )
        self.mac = info[18:24]

    @property
    def batch_fd(self):
        return self.sock.fileno()

    def fileno(self) -> int:
        return self.sock.fileno()

    def recv_frames(self, max_frames: int) -> List[bytes]:
        return _drain_fd_socket(self.sock, max_frames)

    def send_frame(self, frame: bytes) -> None:
        try:
            self.sock.send(frame)
        except (BlockingIOError, OSError):
            pass  # tx queue full: drop (counted by the daemon)

    def close(self) -> None:
        self.sock.close()


class TapTransport(Transport):
    """TAP device via /dev/net/tun (requires CAP_NET_ADMIN)."""

    def __init__(self, name: str):
        self.name = name
        self.fd = os.open("/dev/net/tun", os.O_RDWR | os.O_NONBLOCK)
        ifr = struct.pack("16sH22s", name.encode()[:15],
                          IFF_TAP | IFF_NO_PI, b"")
        fcntl.ioctl(self.fd, TUNSETIFF, ifr)
        self.mac = b"\x02" + os.urandom(5)

    def fileno(self) -> int:
        return self.fd

    def recv_frames(self, max_frames: int) -> List[bytes]:
        out: List[bytes] = []
        while len(out) < max_frames:
            try:
                data = os.read(self.fd, 65535)
            except BlockingIOError:
                break
            except OSError:
                break
            if not data:
                break
            out.append(data)
        return out

    def send_frame(self, frame: bytes) -> None:
        try:
            os.write(self.fd, frame)
        except (BlockingIOError, OSError):
            pass

    def close(self) -> None:
        os.close(self.fd)


def make_transport(kind: str, arg: str) -> Transport:
    """Transport factory used by the daemon CLI and the control channel
    (attach): afpacket:IFNAME | tap:NAME | fd:N."""
    if kind == "afpacket":
        return AfPacketTransport(arg)
    if kind == "tap":
        return TapTransport(arg)
    if kind == "fd":
        return SocketPairTransport(
            socket.socket(fileno=int(arg)), name=f"fd{arg}"
        )
    raise ValueError(f"unknown transport kind {kind!r}")


class SocketPairTransport(Transport):
    """Frame transport over a SOCK_DGRAM socketpair (tests / dev).

    ``pair()`` returns (inside, outside): `inside` is the daemon's side;
    `outside` plays the wire — tests send/receive raw frames through it.
    """

    def __init__(self, sock: socket.socket, name: str = "pair"):
        self.name = name
        self.sock = sock
        self.sock.setblocking(False)
        self.mac = b"\x02" + os.urandom(5)

    @classmethod
    def pair(cls, name: str = "pair") -> Tuple["SocketPairTransport",
                                               "SocketPairTransport"]:
        a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_DGRAM)
        for s in (a, b):
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4 << 20)
                s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
            except OSError:
                pass
        return cls(a, f"{name}-in"), cls(b, f"{name}-out")

    @property
    def batch_fd(self):
        return self.sock.fileno()

    def fileno(self) -> int:
        return self.sock.fileno()

    def recv_frames(self, max_frames: int) -> List[bytes]:
        return _drain_fd_socket(self.sock, max_frames)

    def send_frame(self, frame: bytes) -> None:
        try:
            self.sock.send(frame)
        except (BlockingIOError, OSError):
            pass

    def close(self) -> None:
        self.sock.close()
