"""The node agent: wiring of all plugins + CNI server + node networking.

Reference analog: the contiv-agent process — flavors/contiv DI wiring,
plugins/contiv (remoteCNIserver, node events, node-ID allocation).
"""
