"""Cluster-unique node ID allocation via kvstore compare-and-put.

Each agent claims the smallest free uint8 ID by CAS-inserting its node
name under ``allocatedIDs/<id>``; on restart it finds and reuses its
existing claim. The allocator also publishes the node's data-plane IP and
management IP for other nodes' node-event handlers to consume.

Reference: plugins/contiv/node_id_allocator.go (getID :77,
writeIfNotExists :178, updateEtcdEntry :133).
"""

from __future__ import annotations

from typing import Dict, Optional

from vpp_tpu.kvstore.store import KVStore

ID_PREFIX = "allocatedIDs/"
MAX_ID = 255


class NodeIDAllocator:
    def __init__(self, store: KVStore, node_name: str):
        self.store = store
        self.node_name = node_name
        self.node_id: Optional[int] = None

    def get_or_allocate(self) -> int:
        """Find this node's existing claim or CAS-claim the smallest free ID."""
        if self.node_id is not None:
            return self.node_id
        # Reuse an existing claim (agent restart).
        for key, val in self.store.list_values(ID_PREFIX).items():
            if isinstance(val, dict) and val.get("name") == self.node_name:
                self.node_id = int(key[len(ID_PREFIX):])
                return self.node_id
        # Claim the smallest free ID; retry on CAS races with other agents.
        for attempt in range(MAX_ID):
            taken = {
                int(k[len(ID_PREFIX):]) for k in self.store.list_keys(ID_PREFIX)
            }
            candidate = next(
                (i for i in range(1, MAX_ID + 1) if i not in taken), None
            )
            if candidate is None:
                raise RuntimeError("node ID space exhausted")
            if self.store.compare_and_put(
                ID_PREFIX + str(candidate), None, {"name": self.node_name}
            ):
                self.node_id = candidate
                return candidate
        raise RuntimeError("node ID space exhausted")

    def publish_ips(self, node_ip: str, mgmt_ip: str = "") -> None:
        """Publish this node's data-plane and management IPs for peers."""
        if self.node_id is None:
            raise RuntimeError("allocate an ID before publishing IPs")
        self.store.put(
            ID_PREFIX + str(self.node_id),
            {"name": self.node_name, "ip": node_ip, "mgmt_ip": mgmt_ip},
        )

    def list_nodes(self) -> Dict[int, dict]:
        """All known nodes: id -> {name, ip?, mgmt_ip?}."""
        out = {}
        for key, val in self.store.list_values(ID_PREFIX).items():
            try:
                out[int(key[len(ID_PREFIX):])] = val
            except ValueError:
                continue
        return out

    def release(self) -> None:
        if self.node_id is not None:
            self.store.delete(ID_PREFIX + str(self.node_id))
            self.node_id = None
