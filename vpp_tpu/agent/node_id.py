"""Cluster-unique node ID allocation via kvstore compare-and-put.

Each agent claims the smallest free uint8 ID by CAS-inserting its node
name under ``allocatedIDs/<id>``; on restart it finds and reuses its
existing claim. The allocator also publishes the node's data-plane IP and
management IP for other nodes' node-event handlers to consume.

Reference: plugins/contiv/node_id_allocator.go (getID :77,
writeIfNotExists :178, updateEtcdEntry :133).
"""

from __future__ import annotations

from typing import Dict, Optional

from vpp_tpu.kvstore.store import KVStore

ID_PREFIX = "allocatedIDs/"
# lease-attached liveness keys: present while the node's agent keeps
# its lease alive; expiry (crash, partition) deletes the key and every
# peer's watch removes the routes toward that node. The ID claim itself
# stays persistent so a restarting node reuses its ID (the reference
# keeps allocations in etcd; liveness is the etcd-lease analog).
LIVENESS_PREFIX = "nodeliveness/"
MAX_ID = 255


class NodeIDAllocator:
    def __init__(self, store: KVStore, node_name: str,
                 liveness_ttl_s: float = 15.0):
        self.store = store
        self.node_name = node_name
        self.node_id: Optional[int] = None
        self.liveness_ttl_s = liveness_ttl_s
        self._lease: Optional[int] = None
        self._liveness_info: Optional[dict] = None

    def get_or_allocate(self) -> int:
        """Find this node's existing claim or CAS-claim the smallest free ID."""
        if self.node_id is not None:
            return self.node_id
        # Reuse an existing claim (agent restart).
        for key, val in self.store.list_values(ID_PREFIX).items():
            if isinstance(val, dict) and val.get("name") == self.node_name:
                self.node_id = int(key[len(ID_PREFIX):])
                return self.node_id
        # Claim the smallest free ID; retry on CAS races with other agents.
        for attempt in range(MAX_ID):
            taken = {
                int(k[len(ID_PREFIX):]) for k in self.store.list_keys(ID_PREFIX)
            }
            candidate = next(
                (i for i in range(1, MAX_ID + 1) if i not in taken), None
            )
            if candidate is None:
                raise RuntimeError("node ID space exhausted")
            if self.store.compare_and_put(
                ID_PREFIX + str(candidate), None, {"name": self.node_name}
            ):
                self.node_id = candidate
                return candidate
        raise RuntimeError("node ID space exhausted")

    def publish_ips(self, node_ip: str, mgmt_ip: str = "") -> None:
        """Publish this node's data-plane and management IPs for peers."""
        if self.node_id is None:
            raise RuntimeError("allocate an ID before publishing IPs")
        self.store.put(
            ID_PREFIX + str(self.node_id),
            {"name": self.node_name, "ip": node_ip, "mgmt_ip": mgmt_ip},
        )

    def publish_liveness(self, node_ip: str, mgmt_ip: str = "") -> int:
        """Publish a lease-attached liveness key; keep it alive with
        liveness_keepalive() from the agent maintenance loop. Expiry
        (crash/partition) auto-deletes the key — peers' node watches see
        the DELETE and tear down routes to this node."""
        if self.node_id is None:
            raise RuntimeError("allocate an ID before publishing liveness")
        self._lease = self.store.lease_grant(self.liveness_ttl_s)
        self._liveness_info = {
            "name": self.node_name, "ip": node_ip, "mgmt_ip": mgmt_ip,
        }
        self.store.put(
            LIVENESS_PREFIX + str(self.node_id), self._liveness_info,
            lease=self._lease,
        )
        return self._lease

    def liveness_keepalive(self) -> bool:
        """Refresh the liveness lease; re-grants + re-publishes if the
        lease was lost (kvserver restart, long partition)."""
        if self._lease is None or self._liveness_info is None:
            return False
        if self.store.lease_keepalive(self._lease):
            return True
        self._lease = self.store.lease_grant(self.liveness_ttl_s)
        self.store.put(
            LIVENESS_PREFIX + str(self.node_id), self._liveness_info,
            lease=self._lease,
        )
        return True

    def list_live_nodes(self) -> Dict[int, dict]:
        """Nodes with a current liveness key: id -> {name, ip, mgmt_ip}."""
        out = {}
        for key, val in self.store.list_values(LIVENESS_PREFIX).items():
            try:
                out[int(key[len(LIVENESS_PREFIX):])] = val
            except ValueError:
                continue
        return out

    def list_nodes(self) -> Dict[int, dict]:
        """All known nodes: id -> {name, ip?, mgmt_ip?}."""
        out = {}
        for key, val in self.store.list_values(ID_PREFIX).items():
            try:
                out[int(key[len(ID_PREFIX):])] = val
            except ValueError:
                continue
        return out

    def release(self) -> None:
        if self._lease is not None:
            try:
                self.store.lease_revoke(self._lease)
            except Exception:  # noqa: BLE001 — store may be gone
                pass
            self._lease = None
        if self.node_id is not None:
            self.store.delete(ID_PREFIX + str(self.node_id))
            self.node_id = None
