"""ContivRule: the canonical 5-tuple policy rule with a total order.

This is the most basic policy rule definition that every renderer (and the
TPU data plane) must support, together with the total order used to keep
rule tables sorted most-specific-first.

Reference semantics: plugins/policy/renderer/api.go:65-136 (ContivRule,
Compare) and plugins/policy/utils/utils.go (CompareIPNets, ComparePorts).
Re-designed for Python: networks are ``ipaddress.IPv4Network`` /
``IPv6Network`` instances or ``None`` for "match all".
"""

from __future__ import annotations

import enum
import ipaddress
from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Union

IPNetwork = Union[ipaddress.IPv4Network, ipaddress.IPv6Network]

# Port number 0 stands for "any port".
ANY_PORT = 0


class PodID(NamedTuple):
    """Identifier of a pod: (namespace, name).

    Reference: plugins/ksr/model/pod/keyer.go (podmodel.ID).
    """

    namespace: str
    name: str

    def __str__(self) -> str:  # "<ns>/<name>" form used in ETCD keys and logs
        return f"{self.namespace}/{self.name}"

    @classmethod
    def parse(cls, s: str) -> "PodID":
        ns, _, name = s.partition("/")
        return cls(ns, name)


class Action(enum.IntEnum):
    """Rule action. Reference: renderer/api.go:139-147."""

    DENY = 0
    PERMIT = 1


class Protocol(enum.IntEnum):
    """L4 protocol of a rule. Reference: renderer/api.go:161-169.

    The reference's renderer layer only distinguishes TCP/UDP (ICMP and
    OTHER are handled by explicit appended rules in the ACL renderer); we
    additionally carry ANY/ICMP through the IR so the TPU tables can encode
    them natively rather than via renderer-specific appendices.
    """

    TCP = 0
    UDP = 1
    ICMP = 2
    ANY = 3

    @property
    def ip_proto(self) -> int:
        """IANA protocol number (ANY has none; returns -1)."""
        return {Protocol.TCP: 6, Protocol.UDP: 17, Protocol.ICMP: 1}.get(self, -1)


@dataclass(frozen=True)
class ContivRule:
    """An n-tuple rule: action + L3 src/dst networks + L4 protocol/ports.

    ``src_network``/``dest_network`` of ``None`` and port ``0`` mean
    "match all". Instances are immutable and hashable so they can be used
    directly as dict keys (the renderer cache dedups tables by rule lists).

    Reference: plugins/policy/renderer/api.go:65-77.
    """

    action: Action
    src_network: Optional[IPNetwork] = None
    dest_network: Optional[IPNetwork] = None
    protocol: Protocol = Protocol.TCP
    src_port: int = ANY_PORT
    dest_port: int = ANY_PORT

    def __str__(self) -> str:
        src = str(self.src_network) if self.src_network is not None else "ANY"
        dst = str(self.dest_network) if self.dest_network is not None else "ANY"
        sp = str(self.src_port) if self.src_port else "ANY"
        dp = str(self.dest_port) if self.dest_port else "ANY"
        return (
            f"Rule <{self.action.name} {src}[{self.protocol.name}:{sp}]"
            f" -> {dst}[{self.protocol.name}:{dp}]>"
        )

    # Total order (see compare_rules); enables `sorted(rules)`.
    def __lt__(self, other: "ContivRule") -> bool:
        return compare_rules(self, other) < 0


def compare_ints(a: int, b: int) -> int:
    return (a > b) - (a < b)


def compare_ports(a: int, b: int) -> int:
    """Port order: 0 (= all ports) is *higher* than any specific port.

    Reference: plugins/policy/utils/utils.go ComparePorts.
    """
    if a == b:
        return 0
    if a == ANY_PORT:
        return 1
    if b == ANY_PORT:
        return -1
    return compare_ints(a, b)


def compare_ip_nets(a: Optional[IPNetwork], b: Optional[IPNetwork]) -> int:
    """Network order such that a ⊂ b ⇒ a < b; None (= 0/0) is the maximum.

    Reference: plugins/policy/utils/utils.go CompareIPNets.
    """
    if a is None:
        return 0 if b is None else 1
    if b is None:
        return -1

    # IPv4 sorts before IPv6.
    a4, b4 = a.version == 4, b.version == 4
    if a4 != b4:
        return -1 if a4 else 1

    # Same common prefix => longer (more specific) prefix sorts first.
    common = min(a.prefixlen, b.prefixlen)
    a_net = int(a.network_address) >> (a.max_prefixlen - common) if common else 0
    b_net = int(b.network_address) >> (b.max_prefixlen - common) if common else 0
    if a_net == b_net:
        return compare_ints(b.prefixlen, a.prefixlen)

    # Disjoint subnets: arbitrary but total order (by mask desc, then address).
    mask_order = compare_ints(b.prefixlen, a.prefixlen)
    if mask_order != 0:
        return mask_order
    return compare_ints(int(a.network_address), int(b.network_address))


def compare_rules(a: ContivRule, b: ContivRule) -> int:
    """Total order over rules: if a matches a subset of b's traffic, a < b.

    Order of significance: protocol, src net, dst net, src port, dst port,
    action. Reference: renderer/api.go:110-136.
    """
    for cmp in (
        compare_ints(int(a.protocol), int(b.protocol)),
        compare_ip_nets(a.src_network, b.src_network),
        compare_ip_nets(a.dest_network, b.dest_network),
        compare_ports(a.src_port, b.src_port),
        compare_ports(a.dest_port, b.dest_port),
    ):
        if cmp != 0:
            return cmp
    return compare_ints(int(a.action), int(b.action))


def compare_rule_lists(a: List[ContivRule], b: List[ContivRule]) -> int:
    """Lexicographic order over sorted rule lists (used for table dedup)."""
    for ra, rb in zip(a, b):
        cmp = compare_rules(ra, rb)
        if cmp != 0:
            return cmp
    return compare_ints(len(a), len(b))


def allow_all_tcp() -> ContivRule:
    """PERMIT ANY->ANY TCP. Reference: cache_impl.go allowAllTCP."""
    return ContivRule(action=Action.PERMIT, protocol=Protocol.TCP)


def allow_all_udp() -> ContivRule:
    """PERMIT ANY->ANY UDP. Reference: cache_impl.go allowAllUDP."""
    return ContivRule(action=Action.PERMIT, protocol=Protocol.UDP)


def one_host_subnet(addr: str) -> IPNetwork:
    """The /32 (or /128) subnet containing only the given host address.

    Reference: plugins/policy/utils/utils.go GetOneHostSubnet.
    """
    ip = ipaddress.ip_address(addr)
    return ipaddress.ip_network(f"{ip}/{ip.max_prefixlen}")


def rule_matches(
    rule: ContivRule,
    src_ip: str,
    dst_ip: str,
    protocol: Protocol,
    src_port: int,
    dst_port: int,
) -> bool:
    """Pure-Python oracle: does the rule match the given 5-tuple?

    Used by tests and the mock classification engine to cross-check the
    TPU kernels (the reference's analog is mock/aclengine).
    """
    if rule.protocol != Protocol.ANY and protocol != rule.protocol:
        return False
    if rule.src_network is not None and ipaddress.ip_address(src_ip) not in rule.src_network:
        return False
    if rule.dest_network is not None and ipaddress.ip_address(dst_ip) not in rule.dest_network:
        return False
    if rule.src_port != ANY_PORT and src_port != rule.src_port:
        return False
    if rule.dest_port != ANY_PORT and dst_port != rule.dest_port:
        return False
    return True
