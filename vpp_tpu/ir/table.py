"""ContivRuleTable: an ordered rule table (local per-pod-set or node-global).

Rules are kept sorted by the total order from ``vpp_tpu.ir.rule`` so that a
rule matching a subset of another rule's traffic precedes it — the order a
first-match classifier must evaluate them in.

Reference: plugins/policy/renderer/cache/cache_api.go:199-260 and the
insert/remove logic of ContivRuleTable in the same package.
"""

from __future__ import annotations

import bisect
import enum
from typing import Callable, List, Optional, Set

from vpp_tpu.ir.rule import ContivRule, PodID, compare_rules

# The single node-global table is always identified by this ID.
GLOBAL_TABLE_ID = "NODE-GLOBAL"


class TableType(enum.IntEnum):
    LOCAL = 0
    GLOBAL = 1


class ContivRuleTable:
    """Ordered set of ContivRules + the set of pods the table is assigned to.

    Local tables are immutable once published (a different rule set is a new
    table); the global table is rebuilt per transaction. ``private`` lets a
    renderer attach its device-specific compiled form (e.g. the TPU renderer
    stores the packed int32 rule matrix here).
    """

    def __init__(self, table_id: str, table_type: Optional[TableType] = None):
        self.id = table_id
        if table_type is None:
            table_type = TableType.GLOBAL if table_id == GLOBAL_TABLE_ID else TableType.LOCAL
        self.type = table_type
        self.rules: List[ContivRule] = []
        self.pods: Set[PodID] = set()
        self.private = None

    @property
    def num_of_rules(self) -> int:
        return len(self.rules)

    def insert_rule(self, rule: ContivRule) -> bool:
        """Insert keeping sort order; returns False if already present."""
        idx = bisect.bisect_left(self.rules, rule)
        if idx < len(self.rules) and compare_rules(self.rules[idx], rule) == 0:
            return False
        self.rules.insert(idx, rule)
        return True

    def remove_by_predicate(self, pred: Callable[[ContivRule], bool]) -> int:
        """Remove all rules matching the predicate; returns removed count."""
        kept = [r for r in self.rules if not pred(r)]
        removed = len(self.rules) - len(kept)
        self.rules = kept
        return removed

    def has_rule(self, rule: ContivRule) -> bool:
        idx = bisect.bisect_left(self.rules, rule)
        return idx < len(self.rules) and compare_rules(self.rules[idx], rule) == 0

    def copy(self) -> "ContivRuleTable":
        """Copy with independent pod set; rules list is copied (entries shared —
        ContivRule is immutable so sharing is safe)."""
        t = ContivRuleTable(self.id, self.type)
        t.rules = list(self.rules)
        t.pods = set(self.pods)
        t.private = self.private
        return t

    def __str__(self) -> str:
        pods = ", ".join(sorted(str(p) for p in self.pods))
        return (
            f"Table <{self.id} {self.type.name} pods=[{pods}] "
            f"rules={[str(r) for r in self.rules]}>"
        )
