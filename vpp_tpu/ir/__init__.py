"""Canonical intermediate representation shared by the control plane and renderers."""

from vpp_tpu.ir.rule import (
    ANY_PORT,
    Action,
    ContivRule,
    PodID,
    Protocol,
    allow_all_tcp,
    allow_all_udp,
    compare_ip_nets,
    compare_ports,
    compare_rule_lists,
    compare_rules,
)
from vpp_tpu.ir.table import GLOBAL_TABLE_ID, ContivRuleTable, TableType

__all__ = [
    "ANY_PORT",
    "Action",
    "ContivRule",
    "PodID",
    "Protocol",
    "allow_all_tcp",
    "allow_all_udp",
    "compare_ip_nets",
    "compare_ports",
    "compare_rule_lists",
    "compare_rules",
    "GLOBAL_TABLE_ID",
    "ContivRuleTable",
    "TableType",
]
