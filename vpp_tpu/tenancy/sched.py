"""Host-side tenancy: frame→tenant classification and weighted-fair
dequeue state for the IO pump (ISSUE 14).

jax-free on purpose (the io/governor.py discipline): these run on the
pump's dispatch thread and in light processes.

:class:`TenantClassifier` mirrors the device derivation on frame column
blocks — per packet ``max`` of the matching tenant prefixes (src OR
dst), a frame classifies as the max over its packets — plus the VXLAN
VNI → tenant map (VNIs terminate host-side, before a packet vector
exists, so the VNI axis lives here and not in the device prefix map).

:class:`TenantScheduler` is the weighted-fair dequeue the latency
governor's single bulk class generalizes into (ROADMAP item 2 / the
ISSUE 13 admission seam): per-tenant FIFO queues of ring-order ids with
virtual-time WFQ — the pump serves the non-empty tenant with the LEAST
virtual time (``served_packets / weight``), so one tenant's backlog
cannot starve the rest, and in brownout it sheds from the tenant with
the MOST backlog per unit weight (the hog) instead of FIFO order.
A tenant returning from idle rebases its virtual time to the active
minimum, so accumulated idleness is not a starvation weapon. All
methods are externally synchronized — the pump calls them under its
``_held_lock``, exactly like the rid bookkeeping they extend.
"""

from __future__ import annotations

import collections
import ipaddress
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

# bounds shared with validate_dataplane_config (tables.py): rate fits
# the int32 refill math of tenancy/derive.py, burst stays clear of the
# clip arithmetic
MAX_RATE = 1 << 16
MAX_BURST = 1 << 30

_ML_MODES = ("inherit", "off", "score", "enforce")
# device encoding of the per-tenant ML mode vector (glb_ml_tnt_mode):
# 0 inherit the global stage, 1 off, 2 score-only, 3 enforce
ML_MODE_CODES = {m: i for i, m in enumerate(_ML_MODES)}


def tenant_entries_from_config(entries: Iterable[dict]) -> List[dict]:
    """Normalize the ``tenants:`` YAML list (cmd/config.py) into full
    entry dicts with defaults. Unknown keys are refused — the
    AgentConfig.from_dict discipline."""
    known = {"id", "name", "prefixes", "vni", "rate", "burst",
             "sess_buckets", "nat_buckets", "weight", "ml_mode",
             "ml_thresh"}
    out = []
    for e in entries or ():
        e = dict(e or {})
        unknown = set(e) - known
        if unknown:
            raise ValueError(
                f"unknown tenant config keys: {sorted(unknown)}")
        if "id" not in e:
            raise ValueError("tenant entry missing 'id'")
        out.append({
            "id": int(e["id"]),
            "name": str(e.get("name", f"tenant-{int(e['id'])}")),
            "prefixes": [str(p) for p in (e.get("prefixes") or ())],
            "vni": (int(e["vni"]) if e.get("vni") is not None else None),
            "rate": int(e.get("rate", 0)),
            "burst": int(e.get("burst", 0)),
            "sess_buckets": int(e.get("sess_buckets", 0)),
            "nat_buckets": int(e.get("nat_buckets", 0)),
            "weight": int(e.get("weight", 1)),
            "ml_mode": str(e.get("ml_mode", "inherit")),
            "ml_thresh": (int(e["ml_thresh"])
                          if e.get("ml_thresh") is not None else None),
        })
    return out


def validate_tenancy_config(dataplane_cfg, entries: Iterable[dict]) -> List[dict]:
    """Fail FAST (the validate_dataplane_config discipline) on a bad
    ``tenants:`` list at YAML load: out-of-range ids, unparsable or
    cross-tenant-overlapping prefixes, a prefix map too large for the
    device plane, rate/burst outside the int32 refill math,
    non-power-of-2 or oversubscribed session slices (including
    leaving NO residual bucket range while an unsliced tenant — the
    implicit default tenant 0 counts — still needs one). Returns the
    normalized entries."""
    entries = tenant_entries_from_config(entries)
    # jax-heavy module: import inside the call (this module stays
    # importable in light processes — the pump thread, the CLI client)
    from vpp_tpu.pipeline.tables import (
        _is_pow2,
        natsess_slots_of,
        tnt_capacity,
    )

    tenants = int(getattr(dataplane_cfg, "tenancy_tenants", 8))
    ways = int(getattr(dataplane_cfg, "sess_ways", 4))
    sess_buckets = int(dataplane_cfg.sess_slots) // ways
    nat_buckets = natsess_slots_of(dataplane_cfg) // ways
    pfx_slots = tnt_capacity(dataplane_cfg)[1]
    seen = set()
    sliced = {"sess": 0, "nat": 0}
    # the implicit default tenant 0 is always derivable (unmatched
    # traffic) and is unsliced unless explicitly registered with a
    # slice — it needs residual bucket range too
    unsliced = {"sess": not any(e["id"] == 0 and e["sess_buckets"]
                                for e in entries),
                "nat": not any(e["id"] == 0 and e["nat_buckets"]
                               for e in entries)}
    n_prefixes = 0
    nets_seen: List[Tuple[int, object]] = []
    for e in entries:
        tid = e["id"]
        if not 0 <= tid < tenants:
            raise ValueError(
                f"tenant id {tid} outside 0..{tenants - 1} "
                f"(dataplane.tenancy_tenants)")
        if tid in seen:
            raise ValueError(f"duplicate tenant id {tid}")
        seen.add(tid)
        for p in e["prefixes"]:
            net = ipaddress.ip_network(p, strict=False)
            if net.version != 4:
                raise ValueError(
                    f"tenant {tid}: prefixes must be IPv4, got {p!r}")
            # cross-tenant overlap would make the device derivation
            # (FIRST matching prefix-map slot, staged in tenant-id
            # order) disagree with the host classifier (max matching
            # tenant) — the same packet billed to different tenants on
            # device vs in the pump. Disjoint prefixes make first-match
            # and max identical. Same-tenant overlap is harmless.
            for other_tid, other_net in nets_seen:
                if other_tid != tid and net.overlaps(other_net):
                    raise ValueError(
                        f"tenant {tid}: prefix {p} overlaps tenant "
                        f"{other_tid}'s {other_net} — tenant prefixes "
                        f"must be disjoint across tenants (device "
                        f"first-match vs host max would diverge)")
            nets_seen.append((tid, net))
            n_prefixes += 1
        if not 0 <= e["rate"] <= MAX_RATE:
            raise ValueError(
                f"tenant {tid}: rate must be 0..{MAX_RATE} tokens/tick, "
                f"got {e['rate']}")
        if not 0 <= e["burst"] <= MAX_BURST:
            raise ValueError(
                f"tenant {tid}: burst must be 0..{MAX_BURST}, "
                f"got {e['burst']}")
        if e["rate"] and not e["burst"]:
            # a limited bucket with zero capacity admits nothing ever —
            # surely a config mistake
            raise ValueError(
                f"tenant {tid}: rate {e['rate']} with burst 0 admits "
                f"no traffic (set burst >= rate)")
        if e["weight"] < 1:
            raise ValueError(
                f"tenant {tid}: weight must be >= 1, got {e['weight']}")
        if e["ml_mode"] not in _ML_MODES:
            raise ValueError(
                f"tenant {tid}: ml_mode must be one of {_ML_MODES}, "
                f"got {e['ml_mode']!r}")
        for kind, total in (("sess", sess_buckets), ("nat", nat_buckets)):
            nbk = e[f"{kind}_buckets"]
            if nbk and not _is_pow2(nbk):
                raise ValueError(
                    f"tenant {tid}: {kind}_buckets must be 0 (unsliced) "
                    f"or a power of two, got {nbk}")
            if nbk > total:
                raise ValueError(
                    f"tenant {tid}: {kind}_buckets {nbk} exceeds the "
                    f"table's {total} buckets")
            sliced[kind] += nbk
            if not nbk:
                unsliced[kind] = True
    if n_prefixes > pfx_slots:
        raise ValueError(
            f"tenant prefixes total {n_prefixes} exceeds the device "
            f"map's {pfx_slots} slots (raise dataplane.tenancy_prefixes)")
    for kind, total in (("sess", sess_buckets), ("nat", nat_buckets)):
        if sliced[kind] > total:
            raise ValueError(
                f"tenant {kind}_buckets oversubscribed: {sliced[kind]} "
                f"> {total} table buckets")
        if unsliced[kind] and sliced[kind] >= total:
            # slices are allocated from the top of the table; every
            # UNSLICED tenant (the implicit default tenant 0 included)
            # hashes into the residual bottom range, which must exist
            raise ValueError(
                f"tenant {kind}_buckets {sliced[kind]} fills the whole "
                f"{total}-bucket table but an unsliced tenant (the "
                f"default tenant counts) still needs residual range — "
                f"leave headroom or slice every tenant incl. id 0")
    return entries


class TenantClassifier:
    """Frame → tenant id for the pump's weighted-fair lanes.

    Mirrors the device derivation (tenancy/derive.py) on a frame's
    column block: per packet, the max tenant whose prefix matches src
    OR dst (tenant prefixes are validated DISJOINT across tenants at
    config load, so the device's first-match and this max derive
    identically); a frame classifies as the max over its packets
    (frames are the pump's scheduling unit). The VNI
    map serves encapsulated ingress where the daemon knows the VNI
    before any header parse.
    """

    def __init__(self, entries: Iterable[dict]):
        entries = tenant_entries_from_config(entries)
        nets: List[Tuple[int, int, int]] = []
        self.weights: Dict[int, int] = {}
        self.names: Dict[int, str] = {}
        self._vni: Dict[int, int] = {}
        for e in entries:
            tid = e["id"]
            self.weights[tid] = e["weight"]
            self.names[tid] = e["name"]
            if e["vni"] is not None:
                self._vni[e["vni"]] = tid
            for p in e["prefixes"]:
                net = ipaddress.ip_network(p, strict=False)
                nets.append((int(net.network_address), int(net.netmask),
                             tid))
        self._net = np.asarray([n for n, _m, _t in nets], np.uint32)
        self._mask = np.asarray([m for _n, m, _t in nets], np.uint32)
        self._tid = np.asarray([t for _n, _m, t in nets], np.int64)

    def weight(self, tid: int) -> int:
        return self.weights.get(tid, 1)

    def tenant_of_vni(self, vni: int) -> int:
        """Tenant of a VXLAN VNI (0 = unmapped → the default tenant)."""
        return self._vni.get(int(vni), 0)

    def packet_tenants(self, src_ip: np.ndarray,
                       dst_ip: np.ndarray) -> np.ndarray:
        """Per-packet tenant ids (int64 [n]) — max matching tenant of
        src or dst, 0 unmatched."""
        src = np.asarray(src_ip, np.uint32)
        dst = np.asarray(dst_ip, np.uint32)
        out = np.zeros(src.shape, np.int64)
        for net, mask, tid in zip(self._net, self._mask, self._tid):
            m = ((src & mask) == net) | ((dst & mask) == net)
            np.maximum(out, np.where(m, tid, 0), out=out)
        return out

    def frame_tenant(self, frame) -> int:
        """Tenant of one rx frame (max over its valid packets)."""
        n = frame.n
        if not n or self._net.size == 0:
            return 0
        c = frame.cols
        return int(self.packet_tenants(
            c["src_ip"][:n], c["dst_ip"][:n]).max())


class TenantScheduler:
    """Virtual-time weighted-fair queues over taken ring-order ids.

    Externally synchronized (the pump's ``_held_lock``). ``push``
    enqueues a classified frame; ``pick``/``pop`` implement WFQ
    service (least virtual time first, vtime advancing by
    ``packets / weight``); ``shed_pick`` names the brownout victim —
    the tenant with the largest backlog per unit weight."""

    def __init__(self, weights: Optional[Dict[int, int]] = None):
        self._w = dict(weights or {})
        self._q: Dict[int, "collections.deque"] = {}
        self._vtime: Dict[int, float] = {}
        self._backlog_pkts: Dict[int, int] = {}
        self.total_frames = 0
        self.total_pkts = 0

    def weight(self, tid: int) -> int:
        return max(1, int(self._w.get(tid, 1)))

    def push(self, tid: int, rid: int, n_pkts: int) -> None:
        q = self._q.get(tid)
        if q is None:
            q = self._q[tid] = collections.deque()
        if not q:
            # idle→active rebase: a tenant cannot bank idle time into
            # a burst that starves currently-active tenants
            active = [self._vtime[t] for t, tq in self._q.items()
                      if tq and t != tid]
            floor = min(active) if active else 0.0
            self._vtime[tid] = max(self._vtime.get(tid, 0.0), floor)
        q.append((rid, int(n_pkts)))
        self._backlog_pkts[tid] = self._backlog_pkts.get(tid, 0) + int(n_pkts)
        self.total_frames += 1
        self.total_pkts += int(n_pkts)

    def active(self) -> List[int]:
        return [t for t, q in self._q.items() if q]

    def pick(self) -> Optional[int]:
        """The WFQ service decision: non-empty tenant with least
        virtual time (ties broken by tenant id for determinism)."""
        best = None
        for t in self.active():
            key = (self._vtime.get(t, 0.0), t)
            if best is None or key < best[0]:
                best = (key, t)
        return None if best is None else best[1]

    def shed_pick(self) -> Optional[int]:
        """The brownout victim: most backlog packets per unit weight —
        per-tenant-weighted shedding, not FIFO (ISSUE 14)."""
        best = None
        for t in self.active():
            key = (self._backlog_pkts.get(t, 0) / self.weight(t), t)
            if best is None or key > best[0]:
                best = (key, t)
        return None if best is None else best[1]

    def pop(self, tid: int, max_pkts: int) -> List[Tuple[int, int]]:
        """Dequeue up to ``max_pkts`` packets of ``tid`` (at least one
        frame), advancing its virtual time. Returns [(rid, n), ...]."""
        q = self._q.get(tid)
        out: List[Tuple[int, int]] = []
        pkts = 0
        while q and (not out or pkts + q[0][1] <= max_pkts):
            rid, n = q.popleft()
            out.append((rid, n))
            pkts += n
        if pkts:
            self._vtime[tid] = self._vtime.get(tid, 0.0) \
                + pkts / self.weight(tid)
            self._backlog_pkts[tid] = max(
                0, self._backlog_pkts.get(tid, 0) - pkts)
            self.total_frames -= len(out)
            self.total_pkts -= pkts
        return out

    def requeue_front(self, tid: int, frames: List[Tuple[int, int]]) -> None:
        """Return un-dispatched frames to the HEAD of their queue (the
        ring-fault fallback path) and roll their service back."""
        q = self._q.setdefault(tid, collections.deque())
        pkts = sum(n for _rid, n in frames)
        q.extendleft(reversed(frames))
        self._vtime[tid] = max(
            0.0, self._vtime.get(tid, 0.0) - pkts / self.weight(tid))
        self._backlog_pkts[tid] = self._backlog_pkts.get(tid, 0) + pkts
        self.total_frames += len(frames)
        self.total_pkts += pkts

    def backlog_pkts(self, tid: int) -> int:
        return self._backlog_pkts.get(tid, 0)

    def snapshot(self) -> Dict[int, dict]:
        """Per-tenant queue state (frames/packets queued, vtime) —
        CLI/collector reads; caller holds the pump's lock."""
        return {
            t: {"frames": len(q), "pkts": self._backlog_pkts.get(t, 0),
                "vtime": self._vtime.get(t, 0.0),
                "weight": self.weight(t)}
            for t, q in self._q.items() if q
        }
