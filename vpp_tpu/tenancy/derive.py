"""Device-side tenancy ops: tenant-id derivation + per-tenant token
buckets + per-tenant accounting planes (ISSUE 14 tentpole).

Gryphon (PAPERS.md) organizes a hyperscale gateway around hierarchical
multi-tenancy; the analog here is a first-class tenant axis derived at
ip4-input and threaded through the fused step:

* **Derivation** is a small masked-compare prefix map shipped in its
  own ``"tenant"`` upload group (pipeline/tables.py): per address the
  FIRST matching slot's tenant id wins (prefixes are validated
  DISJOINT across tenants at config load, so slot order never decides
  between tenants and first-match equals the host classifier's max;
  same-tenant nesting is harmless), and a packet's tenant is
  ``max(tenant(src), tenant(dst))`` — deliberately SYMMETRIC under src/dst swap, so both
  directions of a flow derive the same tenant and the tenant-sliced
  session buckets (ops/session.py) are consistent between the forward
  insert and the reply's reverse lookup. Cross-tenant (east-west)
  flows attribute to the higher tenant id by this rule; unmatched
  addresses are tenant 0, the default tenant. The VXLAN VNI → tenant
  map rides on-device too (the ``tnt_vni`` plane + ``vni_tenant``
  below, ISSUE 19): when the overlay stage decaps a frame INSIDE the
  fused step (ops/vxlan.py vxlan_decap_step), the outer header's VNI
  names the tenant directly and overrides the address-derived id for
  that packet — docs/OVERLAY.md "VNI ↔ tenant pact". Non-overlay
  traffic keeps deriving on addresses.

* **Rate limiting** is a per-tenant token bucket evaluated INSIDE the
  fused step: bucket state (``tnt_tokens``/``tnt_tok_time``, [T]
  int32) rides the tables pytree by reference exactly like the sweep
  cursors — epoch swaps carry it, the persistent ring threads it
  window-to-window, zero io_callbacks. Refill is ``rate`` tokens per
  clock tick up to ``burst``; within one batch, packets of a tenant
  consume in packet order (an exclusive per-tenant prefix count), so
  admission is deterministic and the NumPy oracle in
  tests/test_tenancy.py reproduces it bit-for-bit. ``rate == 0``
  means unlimited. Overage drops are attributed ``DROP_TENANT``
  (graph.py) → ``drops_total{reason="tenant_quota"}``.

* **Accounting** scatter-adds per-tenant rx/goodput/drop counters into
  device-resident [T] planes (the telemetry-plane pattern) — `show
  tenants` and the ``vpp_tpu_tenant_*`` families read host copies of
  a few dozen bytes, never columns.

All magnitudes stay inside int32: refill clamps the idle gap at 2^14
ticks and validate_dataplane_config bounds ``rate`` at 2^16, so
``rate * dt <= 2^30`` — and the refill caps the INCREMENT at the
bucket's remaining headroom before adding, so the sum never leaves
int32 either (``tokens + rate*dt`` alone reaches 2^31 at the
inclusive bounds).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from vpp_tpu.pipeline.tables import DataplaneTables
from vpp_tpu.pipeline.vector import PacketVector

# refill clamp: bounds rate * dt inside int32 with rate <= 2^16
# (validate_dataplane_config); a bucket idle longer than 2^14 ticks
# (~27 min at 10 ticks/s) refills to burst anyway
_DT_CLAMP = 1 << 14


def addr_tenant(tables: DataplaneTables, addr: jnp.ndarray) -> jnp.ndarray:
    """Tenant id of each address ([P] uint32 → [P] int32): the FIRST
    prefix-map slot whose masked network matches wins (cross-tenant
    prefixes are validated disjoint, so slot order never picks
    between tenants); no match = tenant 0 (the default tenant)."""
    hit = (
        ((addr[:, None] & tables.tnt_pfx_mask[None, :])
         == tables.tnt_pfx_net[None, :])
        & (tables.tnt_pfx_id[None, :] >= 0)
    )
    any_hit = jnp.any(hit, axis=1)
    first = jnp.argmax(hit, axis=1)
    return jnp.where(any_hit, tables.tnt_pfx_id[first], 0).astype(jnp.int32)


def key_tenant(tables: DataplaneTables, a: jnp.ndarray,
               b: jnp.ndarray) -> jnp.ndarray:
    """Tenant of an ADDRESS PAIR: ``max(tenant(a), tenant(b))`` —
    symmetric by construction, which is what makes tenant-sliced
    session/NAT buckets consistent between a forward flow's insert key
    and the reply's lookup key (both present the same unordered
    address pair, whatever NAT did to the header in between)."""
    return jnp.maximum(addr_tenant(tables, a), addr_tenant(tables, b))


def vni_tenant(tables: DataplaneTables,
               vni: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Tenant of each VXLAN VNI ([P] int32 → (tid [P] int32, known [P]
    bool)): the ``tnt_vni`` plane maps tenant id → configured VNI
    (-1 = none); a decapped frame's VNI names its tenant DIRECTLY
    (ISSUE 19 — no address derivation on overlay traffic). Unknown or
    negative VNIs come back ``known=False`` and the overlay stage
    fails closed (DROP_OVERLAY) — a VNI that names no tenant must
    never be admitted as tenant 0 traffic."""
    plane = tables.tnt_vni
    hit = ((vni[:, None] == plane[None, :])
           & (plane[None, :] >= 0) & (vni[:, None] >= 0))
    known = jnp.any(hit, axis=1)
    tid = jnp.where(known, jnp.argmax(hit, axis=1), 0).astype(jnp.int32)
    return tid, known


def tenant_ids(tables: DataplaneTables, pkts: PacketVector) -> jnp.ndarray:
    """Per-packet tenant id [P] int32 — ``key_tenant`` on the ingress
    header. Pure (no state touched): the two-tier dispatcher may call
    it ahead of the branch without consuming tokens.

    Billing semantics: this is the PRE-NAT header — the wire-cost
    model (bill the bytes as received). A DNAT flow whose backend
    lives in another tenant's prefix therefore bills its two
    directions to different tenants' buckets; the session SLICE key
    is immune (it derives from the post-NAT canonical pair). See
    docs/TENANCY.md "Billing is ingress-header-based"."""
    return key_tenant(tables, pkts.src_ip, pkts.dst_ip)


def tenant_limit(
    tables: DataplaneTables, tid: jnp.ndarray, alive: jnp.ndarray, now
) -> Tuple[DataplaneTables, jnp.ndarray]:
    """One token-bucket round for the batch: refill every tenant's
    bucket by ``rate * ticks_since_last`` (clamped, capped at
    ``burst``), admit each alive packet whose per-tenant arrival rank
    still fits the refilled level, and drop the rest. Returns
    ``(tables', dropped [P])``; call EXACTLY ONCE per fused step (both
    pipeline tiers route through ``graph._tenant_eval``)."""
    T = tables.tnt_rate.shape[0]
    rate = tables.tnt_rate
    burst = tables.tnt_burst
    dt = jnp.clip(now - tables.tnt_tok_time, 0, _DT_CLAMP)
    # overflow-free refill: cap the INCREMENT at the bucket headroom
    # before adding (tokens + rate*dt can reach exactly 2^31 at the
    # validator's inclusive bounds rate=2^16, dt=2^14, burst=tokens=
    # 2^30 — both operands fit int32, their sum does not). A restage
    # that shrank burst below the carried level self-corrects here:
    # negative headroom pulls tok back down to burst.
    tok = tables.tnt_tokens + jnp.minimum(rate * dt,
                                          burst - tables.tnt_tokens)
    limited = rate > 0
    onehot = ((tid[:, None] == jnp.arange(T, dtype=jnp.int32)[None, :])
              & alive[:, None])
    oh = onehot.astype(jnp.int32)
    # exclusive per-tenant prefix count = each packet's arrival rank
    # within its tenant this batch (deterministic in packet order)
    rank = jnp.cumsum(oh, axis=0) - oh
    my_rank = jnp.sum(jnp.where(onehot, rank, 0), axis=1)
    dropped = alive & limited[tid] & (my_rank >= tok[tid])
    admitted = jnp.sum(oh * (~dropped).astype(jnp.int32)[:, None], axis=0)
    tok_after = jnp.where(limited, jnp.clip(tok - admitted, 0, burst),
                          burst)
    return tables._replace(
        tnt_tokens=tok_after.astype(jnp.int32),
        tnt_tok_time=jnp.broadcast_to(
            jnp.asarray(now, jnp.int32), tables.tnt_tok_time.shape),
    ), dropped


def tnt_account(
    tables: DataplaneTables,
    tid: jnp.ndarray,
    rx: jnp.ndarray,
    forwarded: jnp.ndarray,
    rl_dropped: jnp.ndarray,
    quota_fail: jnp.ndarray,
) -> DataplaneTables:
    """Scatter-add the batch into the per-tenant accounting planes
    (device-resident [T] int32, carried by reference across swaps):
    packets received / forwarded (goodput) / rate-limit-dropped /
    session-slice insert failures, per tenant."""
    T = tables.tnt_rx_c.shape[0]

    def bump(plane, mask):
        return plane.at[jnp.where(mask, tid, T)].add(1, mode="drop")

    return tables._replace(
        tnt_rx_c=bump(tables.tnt_rx_c, rx),
        tnt_tx_c=bump(tables.tnt_tx_c, forwarded),
        tnt_rl_c=bump(tables.tnt_rl_c, rl_dropped),
        tnt_qf_c=bump(tables.tnt_qf_c, quota_fail),
    )


def _tenant_occupancy_impl(valid, time, now, max_age, base, nbk):
    """Live sessions per tenant bucket slice: one prefix sum over the
    per-bucket live counts, then a range difference per tenant — O(NB)
    on device, [T] scalars back to the host."""
    live = (valid == 1) & (now - time <= max_age)
    per_bucket = jnp.sum(live.astype(jnp.int32), axis=1)
    n = per_bucket.shape[0]
    cum = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(per_bucket)])
    lo = jnp.clip(base, 0, n)
    hi = jnp.clip(base + nbk, 0, n)
    return cum[hi] - cum[lo]


# Module-level jit (registered in tools/analysis/jit_manifest.py): the
# occupancy probe is an on-demand observability path (`show tenants`,
# the collector) — one compiled program per table geometry, [T] ints
# crossing the transport, never the session columns.
tenant_occupancy = jax.jit(_tenant_occupancy_impl)
