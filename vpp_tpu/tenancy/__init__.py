"""Multi-tenant gateway mode (ISSUE 14): tenant-id derivation,
tenant-sliced table capacity, per-tenant token-bucket rate limiting and
weighted-fair IO scheduling.

Lazily re-exporting (PEP 562, the stats/ and ml/ package pattern): the
host-side scheduler (``sched``) is jax-free and must import in light
processes (the IO daemon, the CLI client); the device ops (``derive``)
pull in jax and load only when a data plane actually uses them.
"""

from __future__ import annotations

_EXPORTS = {
    # host side (jax-free)
    "TenantClassifier": "vpp_tpu.tenancy.sched",
    "TenantScheduler": "vpp_tpu.tenancy.sched",
    "validate_tenancy_config": "vpp_tpu.tenancy.sched",
    "tenant_entries_from_config": "vpp_tpu.tenancy.sched",
    # device side (jax)
    "addr_tenant": "vpp_tpu.tenancy.derive",
    "key_tenant": "vpp_tpu.tenancy.derive",
    "tenant_ids": "vpp_tpu.tenancy.derive",
    "tenant_limit": "vpp_tpu.tenancy.derive",
    "tnt_account": "vpp_tpu.tenancy.derive",
    "tenant_occupancy": "vpp_tpu.tenancy.derive",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
