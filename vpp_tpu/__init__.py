"""vpp_tpu — a TPU-native packet-processing framework.

A from-scratch reimplementation of the capabilities of Contiv-VPP
(reference: wyatuestc/vpp): Kubernetes-driven pod networking with
NetworkPolicy enforcement (ordered 5-tuple ACL classification with
reflective sessions), Service load-balancing (NAT44 DNAT/SNAT), IPAM,
a multi-node overlay — with the per-packet data plane implemented as
JAX/Pallas kernels consuming 256-packet vectors resident in HBM, and
inter-node transport mapped onto ICI/DCN collectives where both ends
are TPU hosts.

Layering (mirrors reference SURVEY.md §1, re-designed TPU-first):

- ``vpp_tpu.ir``        — canonical rule/policy/service IR
                          (reference: plugins/policy/renderer/api.go).
- ``vpp_tpu.renderer``  — the renderer boundary + shared renderer cache
                          (reference: plugins/policy/renderer/cache).
- ``vpp_tpu.ops``       — JAX/Pallas data-plane kernels: ip4 input/lookup,
                          ACL classify, NAT44, VXLAN, sessions
                          (reference: VPP graph nodes, external C).
- ``vpp_tpu.pipeline``  — the fused packet pipeline + device table state
                          (reference: VPP graph scheduler).
- ``vpp_tpu.policy``    — policy cache/processor/configurator
                          (reference: plugins/policy).
- ``vpp_tpu.service``   — service processor/configurator → NAT config
                          (reference: plugins/service).
- ``vpp_tpu.ipam``      — node-ID arithmetic IPAM (reference: plugins/contiv/ipam).
- ``vpp_tpu.ksr``       — K8s state reflectors (reference: plugins/ksr).
- ``vpp_tpu.kvstore``   — etcd-style watchable KV store (reference: cn-infra kvdbsync).
- ``vpp_tpu.agent``     — agent wiring, CNI server (reference: plugins/contiv, cmd/).
- ``vpp_tpu.parallel``  — device-mesh sharding of tables/packet vectors,
                          inter-node ICI overlay.
- ``vpp_tpu.native``    — C++ host runtime (packet rings, parser).
"""

__version__ = "0.1.0"
