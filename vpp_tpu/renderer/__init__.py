"""The renderer boundary: pluggable southbound policy-rendering backends.

Reference: plugins/policy/renderer (api.go + cache/).
"""

from vpp_tpu.renderer.api import PodConfig, PolicyRendererAPI, RendererTxn
from vpp_tpu.renderer.cache import Orientation, RendererCache, TxnChange

__all__ = [
    "PodConfig",
    "PolicyRendererAPI",
    "RendererTxn",
    "Orientation",
    "RendererCache",
    "TxnChange",
]
