"""RendererCache: shared cache computing minimal table diffs for renderers.

The cache folds each pod's ingress+egress ContivRules into a single chosen
orientation, groups identical per-pod rule sets into shared *local tables*,
maintains one node-*global table*, and lets a renderer transaction compute
the minimal set of table changes (`get_changes`) needed to reach the new
configuration.

Orientation semantics (from the vswitch point of view):
- INGRESS: tables match traffic *arriving* from interfaces into the vswitch
  (local table rules have src addr/port wildcarded).
- EGRESS: tables match traffic *leaving* the vswitch through interfaces
  (local table rules have dst addr/port wildcarded).

Reference: plugins/policy/renderer/cache/{cache_api.go,cache_impl.go,
local_tables.go,ports.go} — semantics reproduced, implementation re-done
in Python (sorted lists + dict indexes instead of Go slices/maps).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from vpp_tpu.ir.rule import (
    ANY_PORT,
    Action,
    ContivRule,
    IPNetwork,
    PodID,
    Protocol,
    allow_all_tcp,
    allow_all_udp,
    compare_rule_lists,
)
from vpp_tpu.ir.table import GLOBAL_TABLE_ID, ContivRuleTable, TableType
from vpp_tpu.renderer.api import PodConfig


class Orientation(enum.IntEnum):
    INGRESS = 0
    EGRESS = 1


@dataclass
class TxnChange:
    """One table-level change computed by a transaction.

    ``previous_pods`` is the set of pods previously assigned to the table
    (empty for the global table or a newly added local table).
    """

    table: ContivRuleTable
    previous_pods: Set[PodID] = field(default_factory=set)

    def __str__(self) -> str:
        prev = ", ".join(sorted(str(p) for p in self.previous_pods))
        return f"Change <table: {self.table}, prevPods: [{prev}]>"


# --- Port-set algebra (reference: renderer/cache/ports.go) -----------------

ANY_PORTS = frozenset({ANY_PORT})


def _ports_is_subset(p: Set[int], p2: Set[int]) -> bool:
    if ANY_PORT in p2:
        return True
    if ANY_PORT in p:
        return False
    return all(port in p2 for port in p)


def _ports_intersection(p: Set[int], p2: Set[int]) -> Set[int]:
    if ANY_PORT in p:
        return set(p2)
    if ANY_PORT in p2:
        return set(p)
    return {port for port in p if port in p2}


def _get_allowed_egress_ports(
    src_ip: Optional[IPNetwork], egress: List[ContivRule]
) -> Tuple[Set[int], Set[int]]:
    """Allowed destination (TCP, UDP) ports for traffic *from* src_ip wrt.
    the given egress rules. Reference: ports.go getAllowedEgressPorts."""
    tcp: Set[int] = set()
    udp: Set[int] = set()
    has_deny = False
    for rule in egress:
        if rule.action == Action.DENY:
            # Assumes the only deny rule is the default deny-all (TCP&UDP).
            has_deny = True
            continue
        if (
            rule.src_network is not None
            and src_ip is not None
            and src_ip.network_address not in rule.src_network
        ):
            continue
        # The port algebra models TCP/UDP only; ANY contributes to both,
        # ICMP (portless) to neither — ICMP rules are enforced directly by
        # the data-plane tables, not by this fold.
        if rule.protocol in (Protocol.TCP, Protocol.ANY):
            tcp.add(rule.dest_port)
        if rule.protocol in (Protocol.UDP, Protocol.ANY):
            udp.add(rule.dest_port)
    if not has_deny:
        return set(ANY_PORTS), set(ANY_PORTS)
    return tcp, udp


def _get_allowed_ingress_ports(
    dst_ip: Optional[IPNetwork], ingress: List[ContivRule]
) -> Tuple[Set[int], Set[int]]:
    """Allowed destination (TCP, UDP) ports for traffic *to* dst_ip wrt.
    the given ingress rules. Reference: ports.go getAllowedIngressPorts."""
    tcp: Set[int] = set()
    udp: Set[int] = set()
    has_deny = False
    for rule in ingress:
        if rule.action == Action.DENY:
            has_deny = True
            continue
        if (
            rule.dest_network is not None
            and dst_ip is not None
            and dst_ip.network_address not in rule.dest_network
        ):
            continue
        if rule.protocol in (Protocol.TCP, Protocol.ANY):
            tcp.add(rule.dest_port)
        if rule.protocol in (Protocol.UDP, Protocol.ANY):
            udp.add(rule.dest_port)
    if not has_deny:
        return set(ANY_PORTS), set(ANY_PORTS)
    return tcp, udp


# --- Local-table collection (reference: renderer/cache/local_tables.go) ----


class LocalTables:
    """Collection of local tables ordered by rule lists, with ID/pod indexes.

    A pod is assigned to at most one table at any time.
    """

    def __init__(self) -> None:
        self.tables: List[ContivRuleTable] = []
        self.by_id: Dict[str, ContivRuleTable] = {}
        self.by_pod: Dict[PodID, ContivRuleTable] = {}

    def __iter__(self):
        return iter(list(self.tables))

    def _lookup_idx_by_rules(self, rules: List[ContivRule]) -> int:
        lo, hi = 0, len(self.tables)
        while lo < hi:
            mid = (lo + hi) // 2
            if compare_rule_lists(self.tables[mid].rules, rules) < 0:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def insert(self, table: ContivRuleTable) -> bool:
        if table.id in self.by_id:
            return False
        idx = self._lookup_idx_by_rules(table.rules)
        self.tables.insert(idx, table)
        self.by_id[table.id] = table
        for pod in list(table.pods):
            self.unassign_pod(None, pod)
            self.by_pod[pod] = table
        return True

    def remove(self, table: ContivRuleTable) -> bool:
        if table.id not in self.by_id:
            return False
        self.tables.remove(self.by_id[table.id])
        del self.by_id[table.id]
        for pod in table.pods:
            self.by_pod.pop(pod, None)
        return True

    def assign_pod(self, table: ContivRuleTable, pod: PodID) -> None:
        self.unassign_pod(None, pod)
        table.pods.add(pod)
        self.by_pod[pod] = table

    def unassign_pod(self, table: Optional[ContivRuleTable], pod: PodID) -> None:
        if table is not None:
            table.pods.discard(pod)
        assigned = self.by_pod.get(pod)
        if assigned is not None and (table is None or table is assigned):
            assigned.pods.discard(pod)
            del self.by_pod[pod]

    def lookup_by_id(self, table_id: str) -> Optional[ContivRuleTable]:
        return self.by_id.get(table_id)

    def lookup_by_rules(self, rules: List[ContivRule]) -> Optional[ContivRuleTable]:
        idx = self._lookup_idx_by_rules(rules)
        if idx < len(self.tables) and compare_rule_lists(rules, self.tables[idx].rules) == 0:
            return self.tables[idx]
        return None

    def lookup_by_pod(self, pod: PodID) -> Optional[ContivRuleTable]:
        return self.by_pod.get(pod)

    def get_isolated_pods(self) -> Set[PodID]:
        return {pod for pod, table in self.by_pod.items() if table.num_of_rules > 0}


# --- The cache itself -------------------------------------------------------


class RendererCache:
    """See module docstring. Reference: renderer/cache/cache_impl.go."""

    def __init__(self, orientation: Orientation = Orientation.INGRESS):
        self.orientation = orientation
        self._next_table_id = 0
        self.flush()

    def flush(self) -> None:
        self.config: Dict[PodID, PodConfig] = {}
        self.local_tables = LocalTables()
        self.global_table = ContivRuleTable(GLOBAL_TABLE_ID)

    def new_txn(self) -> "RendererCacheTxn":
        return RendererCacheTxn(self)

    def resync(self, tables: Iterable[ContivRuleTable]) -> None:
        """Replace cache content with dumped tables (e.g. from the device).

        Only the set of tracked pods can be reconstructed, not per-pod rule
        configs — follow a resync with a txn updating still-present pods and
        removing the rest.
        """
        config: Dict[PodID, PodConfig] = {}
        allocated: Set[str] = set()
        local = LocalTables()
        global_table = ContivRuleTable(GLOBAL_TABLE_ID)

        for table in tables:
            if table is None:
                continue
            # Copy: the cache must own its tables — later commits mutate pod
            # assignments in place and must not corrupt the caller's dump
            # (or another cache still holding the same objects).
            table = table.copy()
            if table.type == TableType.GLOBAL:
                global_table = table
                continue
            if not table.pods:
                continue
            if table.id in allocated:
                raise ValueError(f"duplicate ContivRuleTable ID: {table.id}")
            allocated.add(table.id)
            for pod in table.pods:
                if pod in config:
                    raise ValueError(f"pod assigned to multiple local tables: {pod}")
                config[pod] = PodConfig()
            local.insert(table)

        self.config = config
        self.local_tables = local
        self.global_table = global_table
        # Never reuse an ID from the dump: bump the generator counter past
        # any counter-shaped IDs (arbitrary foreign IDs cannot collide with
        # the "T%08d" namespace).
        for table_id in allocated:
            if table_id.startswith("T") and table_id[1:].isdigit():
                self._next_table_id = max(self._next_table_id, int(table_id[1:]) + 1)

    # View
    def get_pod_config(self, pod: PodID) -> Optional[PodConfig]:
        return self.config.get(pod)

    def get_all_pods(self) -> Set[PodID]:
        return set(self.config.keys())

    def get_isolated_pods(self) -> Set[PodID]:
        return self.local_tables.get_isolated_pods()

    def get_local_table_by_pod(self, pod: PodID) -> Optional[ContivRuleTable]:
        table = self.local_tables.lookup_by_pod(pod)
        if table is not None and table.num_of_rules == 0:
            return None
        return table

    def get_global_table(self) -> ContivRuleTable:
        return self.global_table

    def _generate_table_id(self) -> str:
        # Monotonic counter: IDs are never reused, so no tracking set is
        # needed (an abandoned transaction merely skips a few IDs).
        table_id = f"T{self._next_table_id:08d}"
        self._next_table_id += 1
        return table_id


class RendererCacheTxn:
    """Transaction over RendererCache; computes tables lazily on demand."""

    def __init__(self, cache: RendererCache):
        self.cache = cache
        self.config: Dict[PodID, PodConfig] = {}
        self.local_tables = LocalTables()
        self.global_table: Optional[ContivRuleTable] = None
        self._up_to_date = False

    # -- updates
    def update(self, pod: PodID, pod_config: PodConfig) -> None:
        self.config[pod] = pod_config
        self._up_to_date = False

    def get_updated_pods(self) -> Set[PodID]:
        return set(self.config.keys())

    def get_removed_pods(self) -> Set[PodID]:
        return {pod for pod, cfg in self.config.items() if cfg.removed}

    # -- view (as-if-committed)
    def get_pod_config(self, pod: PodID) -> Optional[PodConfig]:
        if pod in self.config:
            return self.config[pod]
        return self.cache.get_pod_config(pod)

    def get_all_pods(self) -> Set[PodID]:
        pods = self.cache.get_all_pods()
        for pod, cfg in self.config.items():
            if cfg.removed:
                pods.discard(pod)
            else:
                pods.add(pod)
        return pods

    def get_isolated_pods(self) -> Set[PodID]:
        # After _refresh_tables every tracked pod has an assignment in the
        # txn's table collection, so the txn view is authoritative.
        if not self._up_to_date:
            self._refresh_tables()
        return self.local_tables.get_isolated_pods()

    def get_local_table_by_pod(self, pod: PodID) -> Optional[ContivRuleTable]:
        if not self._up_to_date:
            self._refresh_tables()
        table = self.local_tables.lookup_by_pod(pod)
        if table is None:
            table = self.cache.local_tables.lookup_by_pod(pod)
        if table is not None and table.num_of_rules == 0:
            return None
        return table

    def get_global_table(self) -> ContivRuleTable:
        if not self._up_to_date:
            self._refresh_tables()
        return self.global_table if self.global_table is not None else self.cache.global_table

    # -- diff + commit
    def get_changes(self) -> List[TxnChange]:
        if not self._up_to_date:
            self._refresh_tables()
        changes: List[TxnChange] = []
        for txn_table in self.local_tables:
            orig = self.cache.local_tables.lookup_by_id(txn_table.id)
            if txn_table.num_of_rules == 0:
                continue
            if not txn_table.pods and orig is None:
                continue  # added and removed within the same txn
            if orig is not None and txn_table.pods == orig.pods:
                continue  # unchanged
            changes.append(
                TxnChange(
                    table=txn_table,
                    previous_pods=set(orig.pods) if orig is not None else set(),
                )
            )
        if self.global_table is not None and compare_rule_lists(
            self.global_table.rules, self.cache.global_table.rules
        ):
            changes.append(TxnChange(table=self.global_table))
        return changes

    def commit(self) -> None:
        if not self._up_to_date:
            self._refresh_tables()
        for txn_table in self.local_tables:
            orig = self.cache.local_tables.lookup_by_id(txn_table.id)
            if orig is not None:
                if not txn_table.pods:
                    self.cache.local_tables.remove(orig)
                elif txn_table.pods != orig.pods:
                    for pod in set(orig.pods):
                        if pod not in txn_table.pods:
                            self.cache.local_tables.unassign_pod(orig, pod)
                    for pod in set(txn_table.pods):
                        if pod not in orig.pods:
                            self.cache.local_tables.assign_pod(orig, pod)
                    orig.private = txn_table.private
            else:
                # Rule-less tables (unisolated/removed pods) are never
                # installed; they only exist to carry assignment changes.
                if txn_table.pods and txn_table.num_of_rules > 0:
                    self.cache.local_tables.insert(txn_table)
        if self.global_table is not None and compare_rule_lists(
            self.global_table.rules, self.cache.global_table.rules
        ):
            self.cache.global_table = self.global_table
        for pod, cfg in self.config.items():
            if cfg.removed:
                self.cache.config.pop(pod, None)
                self.cache.local_tables.unassign_pod(None, pod)
            else:
                self.cache.config[pod] = cfg
        # Prune local tables left with no assigned pods.
        for table in list(self.cache.local_tables):
            if not table.pods:
                self.cache.local_tables.remove(table)

    # -- table building (reference: cache_impl.go refreshTables et al.)
    def _refresh_tables(self) -> None:
        for pod in self.get_all_pods() | self.get_removed_pods():
            pod_cfg = self.get_pod_config(pod)
            if pod_cfg is None:
                continue
            new_table = self._build_local_table(pod, pod_cfg)

            # Pull the pod's original table into the txn if not already there.
            orig = self.cache.local_tables.lookup_by_pod(pod)
            if orig is not None and self.local_tables.lookup_by_id(orig.id) is None:
                self.local_tables.insert(orig.copy())

            # Shared with another table already in the txn?
            txn_table = self.local_tables.lookup_by_rules(new_table.rules)
            if txn_table is not None:
                self.local_tables.assign_pod(txn_table, pod)
                continue

            # Shared with a cache table not yet copied into the txn?
            cache_table = self.cache.local_tables.lookup_by_rules(new_table.rules)
            if cache_table is not None:
                updated = cache_table.copy()
                updated.pods.add(pod)
                self.local_tables.insert(updated)
                self.local_tables.assign_pod(updated, pod)
                continue

            self.local_tables.insert(new_table)
            self.local_tables.assign_pod(new_table, pod)

        self._rebuild_global_table()
        self._up_to_date = True

    def _build_local_table(self, dst_pod: PodID, dst_cfg: PodConfig) -> ContivRuleTable:
        table = ContivRuleTable(self.cache._generate_table_id(), TableType.LOCAL)
        table.pods.add(dst_pod)
        if dst_cfg.removed:
            return table

        # Rules already in the cache orientation are copied verbatim.
        own_rules = dst_cfg.egress if self.cache.orientation == Orientation.EGRESS else dst_cfg.ingress
        for rule in own_rules:
            table.insert_rule(rule)

        # Combine with the opposite direction of every pod on the node.
        for src_pod in self.get_all_pods():
            src_cfg = self.get_pod_config(src_pod)
            if src_cfg is not None:
                self._install_local_rules(table, dst_cfg, src_cfg)

        # Explicitly allow traffic not matched by any rule. A rule counts as
        # "total" for its protocol only if every match dimension is
        # wildcarded (the reference omits the src_port check because its
        # configurator never emits src-port rules; our IR allows them, so
        # check it — otherwise a src-port-specific permit would suppress
        # the allow-all append and default-deny everything else).
        if table.rules:
            all_tcp = any(
                r.dest_port == ANY_PORT and r.src_port == ANY_PORT
                and r.src_network is None and r.dest_network is None
                and r.protocol == Protocol.TCP
                for r in table.rules
            )
            all_udp = any(
                r.dest_port == ANY_PORT and r.src_port == ANY_PORT
                and r.src_network is None and r.dest_network is None
                and r.protocol == Protocol.UDP
                for r in table.rules
            )
            if not all_tcp:
                table.insert_rule(allow_all_tcp())
            if not all_udp:
                table.insert_rule(allow_all_udp())
        return table

    def _install_local_rules(
        self, dst_table: ContivRuleTable, dst_cfg: PodConfig, src_cfg: PodConfig
    ) -> None:
        """Fold the opposite-direction rules of src pod into dst pod's table,
        preserving the combined ingress∧egress semantic in one orientation."""
        egress_oriented = self.cache.orientation == Orientation.EGRESS
        if egress_oriented:
            src_tcp, src_udp = _get_allowed_ingress_ports(dst_cfg.pod_ip, src_cfg.ingress)
            dst_tcp, dst_udp = _get_allowed_egress_ports(src_cfg.pod_ip, dst_cfg.egress)
        else:
            src_tcp, src_udp = _get_allowed_egress_ports(dst_cfg.pod_ip, src_cfg.egress)
            dst_tcp, dst_udp = _get_allowed_ingress_ports(src_cfg.pod_ip, dst_cfg.ingress)

        if not _ports_is_subset(dst_tcp, src_tcp):
            self._install_allowed_ports(
                dst_table, src_cfg.pod_ip, _ports_intersection(dst_tcp, src_tcp), Protocol.TCP
            )
        if not _ports_is_subset(dst_udp, src_udp):
            self._install_allowed_ports(
                dst_table, src_cfg.pod_ip, _ports_intersection(dst_udp, src_udp), Protocol.UDP
            )

    def _install_allowed_ports(
        self,
        dst_table: ContivRuleTable,
        src_pod_ip: Optional[IPNetwork],
        allowed_ports: Set[int],
        protocol: Protocol,
    ) -> None:
        egress_oriented = self.cache.orientation == Orientation.EGRESS

        # Remove the rule subtree rooted at the src pod's one-host subnet.
        def against_src_pod(rule: ContivRule) -> bool:
            if rule.protocol != protocol:
                return False
            net = rule.src_network if egress_oriented else rule.dest_network
            if net is None or src_pod_ip is None:
                return False
            return (
                net.prefixlen == net.max_prefixlen
                and net.network_address == src_pod_ip.network_address
            )

        dst_table.remove_by_predicate(against_src_pod)

        # Explicit rule per allowed port + deny-the-rest.
        for port in allowed_ports:
            kwargs = dict(
                action=Action.PERMIT,
                protocol=protocol,
                src_port=ANY_PORT,
                dest_port=port,
            )
            if egress_oriented:
                kwargs["src_network"] = src_pod_ip
            else:
                kwargs["dest_network"] = src_pod_ip
            dst_table.insert_rule(ContivRule(**kwargs))
        kwargs = dict(
            action=Action.DENY,
            protocol=protocol,
            src_port=ANY_PORT,
            dest_port=ANY_PORT,
        )
        if egress_oriented:
            kwargs["src_network"] = src_pod_ip
        else:
            kwargs["dest_network"] = src_pod_ip
        dst_table.insert_rule(ContivRule(**kwargs))

    def _rebuild_global_table(self) -> None:
        self.global_table = ContivRuleTable(GLOBAL_TABLE_ID)
        egress_oriented = self.cache.orientation == Orientation.EGRESS
        for pod in self.get_all_pods():
            cfg = self.get_pod_config(pod)
            if cfg is None:
                continue
            rules = cfg.ingress if egress_oriented else cfg.egress
            for rule in rules:
                if egress_oriented:
                    rule = ContivRule(
                        action=rule.action,
                        src_network=cfg.pod_ip,
                        dest_network=rule.dest_network,
                        protocol=rule.protocol,
                        src_port=rule.src_port,
                        dest_port=rule.dest_port,
                    )
                else:
                    rule = ContivRule(
                        action=rule.action,
                        src_network=rule.src_network,
                        dest_network=cfg.pod_ip,
                        protocol=rule.protocol,
                        src_port=rule.src_port,
                        dest_port=rule.dest_port,
                    )
                self.global_table.insert_rule(rule)
        if self.global_table.num_of_rules > 0:
            self.global_table.insert_rule(allow_all_tcp())
            self.global_table.insert_rule(allow_all_udp())
