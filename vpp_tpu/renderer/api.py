"""PolicyRendererAPI — the southbound contract of the policy engine.

A renderer turns canonical ContivRules into a concrete network stack's
configuration. The policy configurator fans out to every registered
renderer; each renderer decides how rules are installed (for the TPU
renderer: packed int32 rule tables swapped into the device pipeline).

Reference: plugins/policy/renderer/api.go:33-61.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import List, Optional

from vpp_tpu.ir.rule import ContivRule, IPNetwork, PodID


@dataclass
class PodConfig:
    """Rule configuration of one pod as handed to a renderer / renderer cache.

    Reference: renderer/cache/cache_api.go PodConfig.
    """

    pod_ip: Optional[IPNetwork] = None  # one-host subnet (/32)
    ingress: List[ContivRule] = field(default_factory=list)
    egress: List[ContivRule] = field(default_factory=list)
    removed: bool = False


class RendererTxn(abc.ABC):
    """A single rendering transaction.

    ``render`` calls accumulate per-pod rule updates; ``commit`` propagates
    them into the destination network stack atomically (the TPU renderer
    performs one epoch table-swap per commit).
    """

    @abc.abstractmethod
    def render(
        self,
        pod: PodID,
        pod_ip: Optional[IPNetwork],
        ingress: List[ContivRule],
        egress: List[ContivRule],
        removed: bool = False,
    ) -> "RendererTxn":
        """Set the ingress & egress rules for a pod (replacing existing ones).

        Traffic direction is from the vswitch point of view: for ingress
        rules the source IP is unset (match-all), for egress rules the
        destination IP is unset. An empty rule list allows all traffic in
        that direction. ``removed=True`` means the pod was deleted (rules
        empty, pod_ip may be None).
        """

    @abc.abstractmethod
    def commit(self) -> None:
        """Propagate the rendered changes into the network stack."""


class PolicyRendererAPI(abc.ABC):
    """Factory of renderer transactions.

    If ``resync`` is True the supplied configuration completely replaces the
    existing one; otherwise changes are incremental (pods not mentioned stay
    untouched).
    """

    @abc.abstractmethod
    def new_txn(self, resync: bool = False) -> RendererTxn:
        ...
