"""The TPU policy renderer: ContivRules → HBM rule tables.

This is the southbound implementation that makes the policy engine drive
the TPU data plane (the role the reference's ACL renderer plays for the
VPP ACL plugin, plugins/policy/renderer/acl). It builds on the shared
RendererCache for minimal diffs, maps each shared local table to a device
table slot, points pod interfaces at their slots, installs the global
table, and publishes everything as one table-epoch swap per commit.

Orientation: INGRESS — local tables classify traffic entering the
vswitch from a pod's interface, the global table classifies traffic
entering the node from the uplink (the VPPTCP renderer's orientation;
the ACL renderer uses EGRESS — either is expressible here, ingress needs
one classify point per packet instead of two).

Stateful return traffic is admitted by the data plane's reflective
session table (vpp_tpu.ops.session), the analog of the reference's
reflective ACL (acl_renderer.go:40-44).
"""

from __future__ import annotations

from typing import List, Optional

from vpp_tpu.ir.rule import ContivRule, IPNetwork, PodID
from vpp_tpu.ir.table import TableType
from vpp_tpu.pipeline.dataplane import Dataplane
from vpp_tpu.renderer.api import PodConfig, PolicyRendererAPI, RendererTxn
from vpp_tpu.renderer.cache import Orientation, RendererCache


class TpuRenderer(PolicyRendererAPI):
    def __init__(self, dataplane: Dataplane):
        self.dataplane = dataplane
        self.cache = RendererCache(Orientation.INGRESS)

    def new_txn(self, resync: bool = False) -> "TpuRendererTxn":
        return TpuRendererTxn(self, resync)

    def dump_tables(self):
        """Dump the installed tables (for resync verification/tests)."""
        return list(self.cache.local_tables) + [self.cache.get_global_table()]


class TpuRendererTxn(RendererTxn):
    def __init__(self, renderer: TpuRenderer, resync: bool):
        self.renderer = renderer
        self.resync = resync
        if resync:
            # Full replacement: wipe cached state; the txn below re-renders
            # everything, and commit() rebuilds the device tables.
            renderer.cache.flush()
            for table_id in list(renderer.dataplane.table_slots):
                renderer.dataplane.free_table_slot(table_id)
            for pod in list(renderer.dataplane.pod_if):
                renderer.dataplane.assign_pod_table(pod, None)
        self.cache_txn = renderer.cache.new_txn()

    def render(
        self,
        pod: PodID,
        pod_ip: Optional[IPNetwork],
        ingress: List[ContivRule],
        egress: List[ContivRule],
        removed: bool = False,
    ) -> "TpuRendererTxn":
        self.cache_txn.update(
            pod,
            PodConfig(pod_ip=pod_ip, ingress=ingress, egress=egress, removed=removed),
        )
        return self

    def commit(self) -> None:
        dp = self.renderer.dataplane
        with dp.commit_lock:
            self._commit_locked(dp)

    def _commit_locked(self, dp: Dataplane) -> None:
        changes = self.cache_txn.get_changes()
        for change in changes:
            table = change.table
            if table.type == TableType.GLOBAL:
                dp.builder.set_global_table(table.rules)
                continue
            if not table.pods:
                # Table lost all pods: release its device slot.
                dp.free_table_slot(table.id)
                continue
            slot = dp.alloc_table_slot(table.id)
            dp.builder.set_local_table(slot, table.rules)
        self.cache_txn.commit()
        # Reconcile interface→table assignment for every tracked pod: the
        # cache's ingress↔egress folding means a change to one pod's
        # policies can re-shape *other* pods' local tables (e.g. a new
        # policy on a server pod adds pinned rules to every sender's
        # table), so assignments can move for pods outside this txn.
        for pod in self.renderer.cache.get_all_pods():
            table = self.renderer.cache.get_local_table_by_pod(pod)
            dp.assign_pod_table(pod, table.id if table is not None else None)
        for pod in self.cache_txn.get_removed_pods():
            dp.assign_pod_table(pod, None)
        # A resync always publishes (its __init__ already mutated the
        # builder, even when nothing gets re-rendered).
        if changes or self.cache_txn.get_updated_pods() or self.resync:
            dp.builder.txn_label = (
                "policy-resync" if self.resync else "policy-render"
            )
            dp.swap()
