"""The VPPTCP renderer: ContivRule tables → host-stack session rules.

Reference analog: plugins/policy/renderer/vpptcp/vpptcp_renderer.go —
the second registered renderer, filtering host-TCP-stack connections
instead of packets. It shares the RendererCache (INGRESS orientation,
:106-192), converts each pod's local table into LOCAL-scope rules in the
pod's app namespace (GetNsIndex via contiv.API) and the node's global
table into GLOBAL-scope rules, and pushes *batched* add/del deltas
(:269-327) — never a full rewrite — to the session layer. Resync
re-imports the engine dump (:195-238).

The 5-tuple orientation follows where each table sits in the path
(ingress orientation): a pod's LOCAL table filters its *outbound
connects*, so the rule's ``src_*`` fields are the pod-local side and
``dest_*`` the remote side; the GLOBAL table filters *inbound accepts*
entering the node, so there ``dest_*`` is the local (accepting) side
and ``src_*`` the remote initiator.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Set

from vpp_tpu.hoststack.session_rules import (
    GLOBAL_NS,
    RuleAction,
    RuleScope,
    SessionRule,
    SessionRuleEngine,
)
from vpp_tpu.ir.rule import ANY_PORT, Action, ContivRule, IPNetwork, PodID, Protocol
from vpp_tpu.ir.table import ContivRuleTable, TableType
from vpp_tpu.renderer.api import PodConfig, PolicyRendererAPI, RendererTxn
from vpp_tpu.renderer.cache import Orientation, RendererCache

# contiv.API GetNsIndex analog: pod → app namespace index
NsIndexFn = Callable[[PodID], int]


def _rules_for_table(
    table: ContivRuleTable, ns_indexes: List[int]
) -> Set[SessionRule]:
    """Expand one ContivRuleTable into wire session rules.

    A local table shared by k pods expands into k copies of its rules,
    one per pod app-namespace (the engine's table is flat); the global
    table expands once with GLOBAL scope.
    """
    out: Set[SessionRule] = set()
    is_global = table.type == TableType.GLOBAL
    scopes = [(RuleScope.GLOBAL, GLOBAL_NS)] if is_global else [
        (RuleScope.LOCAL, ns) for ns in ns_indexes
    ]
    for rule in table.rules:
        if rule.protocol == Protocol.ICMP:
            continue  # session layer is TCP/UDP only
        protos = (
            [6, 17] if rule.protocol == Protocol.ANY else [rule.protocol.ip_proto]
        )
        src_net = int(rule.src_network.network_address) if rule.src_network else 0
        src_plen = rule.src_network.prefixlen if rule.src_network else 0
        dst_net = int(rule.dest_network.network_address) if rule.dest_network else 0
        dst_plen = rule.dest_network.prefixlen if rule.dest_network else 0
        src_port = 0 if rule.src_port == ANY_PORT else rule.src_port
        dst_port = 0 if rule.dest_port == ANY_PORT else rule.dest_port
        if is_global:
            # accept-side: local = destination, remote = initiator
            lcl = (dst_net, dst_plen, dst_port)
            rmt = (src_net, src_plen, src_port)
        else:
            # connect-side: local = the pod (src), remote = destination
            lcl = (src_net, src_plen, src_port)
            rmt = (dst_net, dst_plen, dst_port)
        for scope, ns in scopes:
            for proto in protos:
                out.add(
                    SessionRule(
                        scope=int(scope),
                        appns_index=ns,
                        transport_proto=proto,
                        lcl_net=lcl[0],
                        lcl_plen=lcl[1],
                        rmt_net=rmt[0],
                        rmt_plen=rmt[1],
                        lcl_port=lcl[2],
                        rmt_port=rmt[2],
                        action=int(RuleAction.ALLOW)
                        if rule.action == Action.PERMIT
                        else int(RuleAction.DENY),
                        # tag left empty: rule identity must not depend on
                        # the (rebuild-varying) table id, or deltas between
                        # epochs stop being minimal.
                    )
                )
    return out


class VpptcpRenderer(PolicyRendererAPI):
    def __init__(self, engine: SessionRuleEngine, ns_index: NsIndexFn):
        self.engine = engine
        self.ns_index = ns_index
        self.cache = RendererCache(Orientation.INGRESS)

    def new_txn(self, resync: bool = False) -> "VpptcpRendererTxn":
        return VpptcpRendererTxn(self, resync)

    def desired_rules(self) -> Set[SessionRule]:
        """The full session-rule set implied by the cache state."""
        want: Set[SessionRule] = set()
        for table in self.cache.local_tables:
            ns_list = [self.ns_index(pod) for pod in table.pods]
            ns_list = [n for n in ns_list if n >= 0]
            if ns_list:
                want |= _rules_for_table(table, ns_list)
        want |= _rules_for_table(self.cache.get_global_table(), [])
        return want

    def dump_rules(self) -> List[SessionRule]:
        return self.engine.dump()


class VpptcpRendererTxn(RendererTxn):
    def __init__(self, renderer: VpptcpRenderer, resync: bool):
        self.renderer = renderer
        self.resync = resync
        if resync:
            renderer.cache.flush()
        self.cache_txn = renderer.cache.new_txn()

    def render(
        self,
        pod: PodID,
        pod_ip: Optional[IPNetwork],
        ingress: List[ContivRule],
        egress: List[ContivRule],
        removed: bool = False,
    ) -> "VpptcpRendererTxn":
        self.cache_txn.update(
            pod,
            PodConfig(pod_ip=pod_ip, ingress=ingress, egress=egress, removed=removed),
        )
        return self

    def commit(self) -> None:
        r = self.renderer
        self.cache_txn.commit()
        # Batched minimal delta at the wire level: one apply() regardless
        # of how many rules changed (vpptcp_renderer.go:269-327). On
        # resync the engine may hold stale rules from before the restart;
        # the same diff covers that (dump = installed, cache = desired).
        installed = set(r.engine.dump())
        desired = r.desired_rules()
        add = desired - installed
        delete = installed - desired
        if add or delete:
            r.engine.apply(add=add, delete=delete)
