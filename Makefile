# CI analog of the reference's Makefile (Makefile:44-70: per-package
# unit tests, -race variants, lint) for a no-external-deps environment.

PY ?= python

.PHONY: test test-race chaos lint verify bench autotune autotune-check all

all: lint test

test:
	$(PY) -m pytest tests/ -q

# Race-amplified run: tests/conftest.py lowers the interpreter's thread
# switch interval to force frequent preemption at the concurrency seams
# (the Go -race analog available to pure Python — races surface as
# corrupted state/assertions in the stress tests rather than reports).
test-race:
	VPP_TPU_RACE=1 $(PY) -m pytest tests/test_concurrency.py tests/test_io.py \
		tests/test_native_ring.py tests/test_kvserver.py \
		tests/test_vcl_preload.py tests/test_multihost_unit.py \
		tests/test_kvstore_fencing.py -q

# Seeded fault-injection schedules (ISSUE 8): kvstore partitions,
# ring fault → dispatch fallback, dispatch fetch/tx faults, torn
# snapshots, reconnect storms — each asserting exact packet/session
# conservation after recovery. Seeds default inside the tests
# (override: VPPT_CHAOS_SEED=n); bounded runtime; also marked `slow`
# so the tier-1 `-m 'not slow'` timing budget never pays for it.
chaos:
	$(PY) -m pytest tests/test_chaos.py -q -m chaos

# Base style pass + the pure-AST analysis passes (tools/analysis/):
# --jax tracer/recompile hygiene, --threads lock discipline,
# --partitions rule completeness (pure import, no jax arrays), and the
# ISSUE 20 device-boundary dataflow passes: --uploads group-staleness,
# --transfers host-fetch allowlisting, --donate use-after-donate. The
# registry passes (--metrics/--counters/--tables) import jax, so
# tier-1 runs them from tests instead (test_exposition / test_acl_bv).
# autotune-check rides along: a drifted tuned/cpu.json is a lint-class
# failure (the committed profile must round-trip the config loader).
lint: autotune-check
	$(PY) tools/lint.py --jax --threads --partitions --uploads \
		--transfers --donate

# Driver-facing headline benchmark (real TPU; one JSON line).
bench:
	$(PY) bench.py

# Config-knob autotuner (ISSUE 16; tools/autotune.py): sweep the
# backend-dependent geometry knobs and write tuned/<backend>.json.
autotune:
	$(PY) tools/autotune.py

# Validate the committed CPU profile round-trips through the SAME
# config loader the agent boots with (knobs land, floor clamps).
autotune-check:
	$(PY) tools/autotune.py --check tuned/cpu.json
